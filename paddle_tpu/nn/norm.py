"""Normalization layers (reference python/paddle/nn/layer/norm.py)."""

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from . import functional as F
from .initializer import Constant
from .layer_base import Layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self.normalized_shape = list(normalized_shape)
        self.epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                self.normalized_shape, attr=weight_attr,
                default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(self.normalized_shape,
                                              attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight, self.bias,
                            self.epsilon)

    def extra_repr(self):
        return f"normalized_shape={self.normalized_shape}, epsilon={self.epsilon}"


class RMSNorm(Layer):
    """RMSNorm — beyond the reference surface; llama-family requirement."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None):
        super().__init__()
        self.epsilon = epsilon
        self.weight = self.create_parameter((hidden_size,), attr=weight_attr,
                                            default_initializer=Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, epsilon=self.epsilon)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.data_format = data_format
        self.use_global_stats = use_global_stats
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter((num_features,), attr=weight_attr,
                                                default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter((num_features,), attr=bias_attr,
                                              is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros(num_features)))
        self.register_buffer("_variance", Tensor(jnp.ones(num_features)))

    def forward(self, x):
        training = self.training and not self.use_global_stats
        out, batch_mean, batch_var = F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=training, momentum=self.momentum, epsilon=self.epsilon,
            data_format=self.data_format,
            use_global_stats=self.use_global_stats)
        if training:
            m = self.momentum
            self._mean.set_value(m * self._mean._data +
                                 (1 - m) * batch_mean._data)
            self._variance.set_value(m * self._variance._data +
                                     (1 - m) * batch_var._data)
        return out


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class BatchNorm(_BatchNormBase):
    """Legacy fluid BatchNorm API shim."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-05,
                 **kwargs):
        super().__init__(num_channels, momentum=momentum, epsilon=epsilon)
        self.act = act

    def forward(self, x):
        out = super().forward(x)
        if self.act == "relu":
            out = F.relu(out)
        return out


class SyncBatchNorm(_BatchNormBase):
    """On TPU, batch-norm stats sync falls out of SPMD (stats computed over the
    global batch under pjit); eager single-chip behaves like BatchNorm.
    Reference: python/paddle/nn/layer/norm.py SyncBatchNorm."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        # structural conversion kept for API parity
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, cls):
            new = cls(layer.num_features, layer.momentum, layer.epsilon,
                      data_format=layer.data_format)
            new.weight = layer.weight
            new.bias = layer.bias
            new._buffers = layer._buffers
            return new
        return layer


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.num_groups = num_groups
        self.epsilon = epsilon
        self.data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            (num_channels,), attr=weight_attr, default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            (num_channels,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self.num_groups, self.weight, self.bias,
                            self.epsilon, self.data_format)


class InstanceNorm2D(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.epsilon = epsilon
        if weight_attr is False:
            self.weight, self.bias = None, None
        else:
            self.weight = self.create_parameter((num_features,), attr=weight_attr,
                                                default_initializer=Constant(1.0))
            self.bias = self.create_parameter((num_features,), attr=bias_attr,
                                              is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, self.weight, self.bias, self.epsilon)


InstanceNorm1D = InstanceNorm2D
InstanceNorm3D = InstanceNorm2D


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW"):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta, self.k)


class SpectralNorm(Layer):
    """Spectral normalization: divide a weight by its largest singular
    value, estimated by persistent power iteration
    (reference python/paddle/nn/layer/norm.py SpectralNorm /
    spectral_norm_hook.py; phi spectral_norm kernel).

    ``forward(weight)`` reshapes the weight so ``dim`` leads ([H, W],
    W = product of the rest), runs ``power_iters`` u/v updates against
    the persistent buffers, and returns ``weight / sigma``.
    """

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12):
        super().__init__()
        self.dim = int(dim)
        self.power_iters = int(power_iters)
        self.eps = float(eps)
        self._shape = list(weight_shape)
        h = int(weight_shape[self.dim])
        w = int(np.prod([d for i, d in enumerate(weight_shape)
                         if i != self.dim]))
        from ..framework.random import get_rng_key

        key = get_rng_key()
        ku, kv = jax.random.split(key)
        u = jax.random.normal(ku, (h,), jnp.float32)
        v = jax.random.normal(kv, (w,), jnp.float32)
        self.register_buffer(
            "weight_u", Tensor(u / jnp.maximum(jnp.linalg.norm(u),
                                               self.eps)))
        self.register_buffer(
            "weight_v", Tensor(v / jnp.maximum(jnp.linalg.norm(v),
                                               self.eps)))

    def forward(self, weight):
        x = weight._data if isinstance(weight, Tensor) else \
            jnp.asarray(weight)
        perm = [self.dim] + [i for i in range(x.ndim) if i != self.dim]
        mat = jnp.transpose(x, perm).reshape(x.shape[self.dim], -1)
        matf = mat.astype(jnp.float32)
        u = self._buffers["weight_u"]._data
        v = self._buffers["weight_v"]._data
        # power iteration runs OUTSIDE the autograd chain (the reference
        # marks u/v stop_gradient and treats sigma's u/v as constants)
        m_const = jax.lax.stop_gradient(matf)
        for _ in range(self.power_iters):
            v = m_const.T @ u
            v = v / jnp.maximum(jnp.linalg.norm(v), self.eps)
            u = m_const @ v
            u = u / jnp.maximum(jnp.linalg.norm(u), self.eps)
        self._buffers["weight_u"].set_value(u)
        self._buffers["weight_v"].set_value(v)
        from ..ops.dispatch import apply_op

        w_t = weight if isinstance(weight, Tensor) else Tensor(x)

        def fn(wd):
            md = jnp.transpose(wd, perm).reshape(
                wd.shape[self.dim], -1).astype(jnp.float32)
            sigma = u @ md @ v
            return (wd.astype(jnp.float32) /
                    jnp.maximum(sigma, self.eps)).astype(wd.dtype)

        return apply_op("spectral_norm", fn, (w_t,), {})
