"""paddle_tpu.nn — layers, functional, initializers.

Mirrors ``paddle.nn`` (reference python/paddle/nn/__init__.py).
"""

from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .layer_base import Layer, ParamAttr, Parameter  # noqa: F401
from .common import (  # noqa: F401
    AlphaDropout,
    Bilinear,
    CosineSimilarity,
    Dropout,
    Dropout2D,
    Embedding,
    Flatten,
    Identity,
    Linear,
    Pad1D,
    Pad2D,
    PixelShuffle,
    Upsample,
)
from .container import (  # noqa: F401
    LayerDict,
    LayerList,
    ParameterList,
    Sequential,
)
from .conv import Conv1D, Conv2D, Conv2DTranspose, Conv3D  # noqa: F401
from .rnn import GRU, LSTM, SimpleRNN  # noqa: F401
from .pooling import (  # noqa: F401
    AdaptiveAvgPool1D,
    AdaptiveAvgPool2D,
    AdaptiveMaxPool2D,
    AvgPool1D,
    AvgPool2D,
    MaxPool1D,
    MaxPool2D,
)
from .norm import (  # noqa: F401
    BatchNorm,
    BatchNorm1D,
    BatchNorm2D,
    BatchNorm3D,
    GroupNorm,
    InstanceNorm1D,
    InstanceNorm2D,
    InstanceNorm3D,
    LayerNorm,
    LocalResponseNorm,
    SpectralNorm,
    RMSNorm,
    SyncBatchNorm,
)
from .activation import (  # noqa: F401
    CELU,
    ELU,
    GELU,
    Hardshrink,
    Hardsigmoid,
    Hardswish,
    Hardtanh,
    LeakyReLU,
    LogSigmoid,
    LogSoftmax,
    Maxout,
    Mish,
    PReLU,
    ReLU,
    ReLU6,
    SELU,
    Sigmoid,
    SiLU,
    Silu,
    Softmax,
    Softplus,
    Softshrink,
    Softsign,
    Swish,
    Tanh,
    Tanhshrink,
)
from .transformer import (  # noqa: F401
    MultiHeadAttention,
    Transformer,
    TransformerDecoder,
    TransformerDecoderLayer,
    TransformerEncoder,
    TransformerEncoderLayer,
)
from .loss import (  # noqa: F401
    BCELoss,
    BCEWithLogitsLoss,
    CrossEntropyLoss,
    KLDivLoss,
    L1Loss,
    MarginRankingLoss,
    MSELoss,
    NLLLoss,
    SmoothL1Loss,
)

import sys as _sys

functional.__name__ = "paddle_tpu.nn.functional"
_sys.modules.setdefault("paddle_tpu.nn.F", functional)
