"""Op registry.

The single-source op registry is the one piece of the reference architecture
kept conceptually (YAML op defs at paddle/phi/api/yaml/ops.yaml fanning out to
API/autograd/bindings; SURVEY §1 "cross-cutting codegen").  Here registration is
a decorator over a pure-jax implementation; autograd comes for free from
``jax.vjp`` in dispatch, and the registry doubles as the coverage table
(analog of the XPU supported-op list precedent,
paddle/phi/backends/xpu/xpu2_op_list.cc).
"""

import functools
import inspect

from .dispatch import apply_op

OPS = {}


class OpSchemaError(TypeError):
    """Raised when a registered op's signature contradicts the reference
    YAML schema and no divergence is recorded in ops/schema_compat.py."""


def _validate_schema(name, jfn):
    """Validate ``jfn``'s signature against the reference YAML schema.

    Returns a {param: default} dict of schema defaults to auto-fill for
    params the implementation left default-less, or None.  Raises
    OpSchemaError when a required schema arg is neither accepted by the
    implementation nor covered by a documented divergence — this is what
    makes the schema the single source the reference's yaml is
    (paddle/phi/api/yaml/ops.yaml + api_gen.py role).
    """
    from .schema import get_schema
    from .schema_compat import SCHEMA_DIVERGENCES

    sch = get_schema(name)
    if sch is None:
        return None
    try:
        sig = inspect.signature(jfn)
    except (TypeError, ValueError):
        return None
    params = sig.parameters
    if any(p.kind in (p.VAR_KEYWORD, p.VAR_POSITIONAL)
           for p in params.values()):
        return None
    div = SCHEMA_DIVERGENCES.get(name, {})
    renames = div.get("renames", {})
    dropped = set(div.get("dropped", ()))
    missing = []
    fill = {}
    for entry in sch["args"]:
        a_name, has_default = entry[1], entry[2]
        default = entry[3] if len(entry) > 3 else None
        impl_name = renames.get(a_name, a_name)
        if impl_name not in params:
            if not has_default and a_name not in dropped:
                missing.append(a_name)
            continue
        p = params[impl_name]
        if (has_default and default is not None
                and p.default is inspect.Parameter.empty
                and p.kind in (p.POSITIONAL_OR_KEYWORD, p.KEYWORD_ONLY)):
            fill[impl_name] = default
    if missing:
        raise OpSchemaError(
            f"op '{name}': implementation signature {list(params)} is "
            f"missing required schema arg(s) {missing} "
            f"(reference paddle/phi/api/yaml). Rename the params to match, "
            f"or record the deliberate divergence in "
            f"paddle_tpu/ops/schema_compat.py")
    return fill or None


class OpDef:
    __slots__ = ("name", "jax_fn", "user_fn", "tags")

    def __init__(self, name, jax_fn, user_fn, tags):
        self.name = name
        self.jax_fn = jax_fn
        self.user_fn = user_fn
        self.tags = tags


def op(opname=None, tags=()):
    """Register a pure-jax function as an eager op.

    The decorated function must be pure jax (operates on jax arrays / pytrees,
    no Tensor objects).  The returned user-facing function accepts Tensors
    anywhere in args/kwargs and records autograd.
    """

    def deco(jfn):
        name = opname or jfn.__name__

        # Schema validation on FIRST registration only (per-call closure
        # re-registrations — dropout & friends — skip it: the import-time
        # signature was already checked and the closure narrows it).
        fill = None
        if name not in OPS:
            fill = _validate_schema(name, jfn)
        if fill:
            # schema-supplied defaults for params the impl left bare:
            # positions precomputed so the hot path pays dict lookups only
            positions = {k: i for i, k in
                         enumerate(inspect.signature(jfn).parameters)}
            fill_pos = [(k, positions[k], v) for k, v in fill.items()]

            @functools.wraps(jfn)
            def user_fn(*args, **kwargs):
                kwargs.pop("name", None)
                for k, idx, v in fill_pos:
                    if len(args) <= idx and k not in kwargs:
                        kwargs[k] = v
                return apply_op(name, jfn, args, kwargs)
        else:
            @functools.wraps(jfn)
            def user_fn(*args, **kwargs):
                kwargs.pop("name", None)
                return apply_op(name, jfn, args, kwargs)

        # First registration wins: several public ops register a
        # closure-capturing inner @op on every call (dropout, rrelu, …);
        # letting those clobber the import-time entry would leave OPS[name]
        # pointing at a narrowed signature.
        if name not in OPS:
            OPS[name] = OpDef(name, jfn, user_fn, tuple(tags))
        return user_fn

    return deco


def raw(name):
    """Get the pure-jax implementation of a registered op (for jit paths)."""
    return OPS[name].jax_fn


def register_external(name, user_fn, jax_fn=None, tags=()):
    """Register an already-wrapped user-facing function under ``name``.

    For ops whose public entry point lives outside the ``@op`` decorator
    (creation/random fns returning Tensors directly, collective wrappers,
    rng-threading wrappers).  Keeps the coverage table honest without
    forcing everything through ``apply_op``.
    """
    if name not in OPS:
        OPS[name] = OpDef(name, jax_fn, user_fn, tuple(tags))
    return user_fn


def coverage(yaml_names=None):
    """Return (registered, total, pct) against an op-name inventory."""
    if yaml_names is None:
        from .inventory import OP_INVENTORY
        yaml_names = OP_INVENTORY
    have = sum(1 for n in yaml_names if n in OPS)
    return have, len(yaml_names), 100.0 * have / max(1, len(yaml_names))


def schema(name):
    """Reference-YAML signature schema for an op (args/outputs/backward/
    inplace), or None.  Single-source parity surface: generated from
    paddle/phi/api/yaml/*.yaml by tools/gen_schema.py."""
    from .schema import get_schema
    return get_schema(name)
