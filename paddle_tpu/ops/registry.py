"""Op registry.

The single-source op registry is the one piece of the reference architecture
kept conceptually (YAML op defs at paddle/phi/api/yaml/ops.yaml fanning out to
API/autograd/bindings; SURVEY §1 "cross-cutting codegen").  Here registration is
a decorator over a pure-jax implementation; autograd comes for free from
``jax.vjp`` in dispatch, and the registry doubles as the coverage table
(analog of the XPU supported-op list precedent,
paddle/phi/backends/xpu/xpu2_op_list.cc).
"""

import functools

from .dispatch import apply_op

OPS = {}


class OpDef:
    __slots__ = ("name", "jax_fn", "user_fn", "tags")

    def __init__(self, name, jax_fn, user_fn, tags):
        self.name = name
        self.jax_fn = jax_fn
        self.user_fn = user_fn
        self.tags = tags


def op(opname=None, tags=()):
    """Register a pure-jax function as an eager op.

    The decorated function must be pure jax (operates on jax arrays / pytrees,
    no Tensor objects).  The returned user-facing function accepts Tensors
    anywhere in args/kwargs and records autograd.
    """

    def deco(jfn):
        name = opname or jfn.__name__

        @functools.wraps(jfn)
        def user_fn(*args, **kwargs):
            kwargs.pop("name", None)
            return apply_op(name, jfn, args, kwargs)

        # First registration wins: several public ops register a
        # closure-capturing inner @op on every call (dropout, rrelu, …);
        # letting those clobber the import-time entry would leave OPS[name]
        # pointing at a narrowed signature.
        if name not in OPS:
            OPS[name] = OpDef(name, jfn, user_fn, tuple(tags))
        return user_fn

    return deco


def raw(name):
    """Get the pure-jax implementation of a registered op (for jit paths)."""
    return OPS[name].jax_fn


def register_external(name, user_fn, jax_fn=None, tags=()):
    """Register an already-wrapped user-facing function under ``name``.

    For ops whose public entry point lives outside the ``@op`` decorator
    (creation/random fns returning Tensors directly, collective wrappers,
    rng-threading wrappers).  Keeps the coverage table honest without
    forcing everything through ``apply_op``.
    """
    if name not in OPS:
        OPS[name] = OpDef(name, jax_fn, user_fn, tuple(tags))
    return user_fn


def coverage(yaml_names=None):
    """Return (registered, total, pct) against an op-name inventory."""
    if yaml_names is None:
        from .inventory import OP_INVENTORY
        yaml_names = OP_INVENTORY
    have = sum(1 for n in yaml_names if n in OPS)
    return have, len(yaml_names), 100.0 * have / max(1, len(yaml_names))


def schema(name):
    """Reference-YAML signature schema for an op (args/outputs/backward/
    inplace), or None.  Single-source parity surface: generated from
    paddle/phi/api/yaml/*.yaml by tools/gen_schema.py."""
    from .schema import get_schema
    return get_schema(name)
