"""Fused ops: attention/FFN/transformer fusions + the XPU-fused op set.

Reference: paddle/phi/kernels/fusion/{gpu,cutlass,onednn,xpu},
paddle/fluid/operators/fused/ (fused_attention_op.cu,
fused_feedforward_op.cu, fused_multi_transformer_op.cu), flash-attn loader
at paddle/phi/backends/dynload/flashattn.h.

TPU design: "fused" is mostly a no-op concept under XLA — these compositions
compile to fused kernels anyway; the ops exist for API/registry parity and
to route the attention core through the Pallas flash kernel
(ops/pallas) where it matters.  The `*_xpu` names mirror the reference's
per-backend fused op list (paddle/phi/backends/xpu/xpu2_op_list.cc
precedent) and map to the same compositions here.
"""

import jax
import jax.numpy as jnp

from .registry import op
from .pallas import flash_attention as _attention_impl


def _ln(x, scale, bias, eps):
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = xf.var(-1, keepdims=True)
    out = (xf - mu) * jax.lax.rsqrt(var + eps)
    if scale is not None:
        out = out * scale.astype(jnp.float32)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(x.dtype)


@op()
def flash_attn(q, k, v, fixed_seed_offset=None, attn_mask=None,
               dropout=0.0, causal=False, return_softmax=False,
               is_test=True, rng_name=""):
    """FlashAttention layout parity: q/k/v [B, T, N, H] → out [B, T, N, H].

    Routes to the Pallas TPU kernel when enabled (ops/pallas), XLA attention
    otherwise.  Reference python surface:
    python/paddle/nn/functional/flash_attention.py:125.
    """
    out = _attention_impl(q, k, v, attn_mask=attn_mask, is_causal=causal,
                          dropout_p=0.0 if is_test else dropout)
    if return_softmax:
        return out, None
    return out


@op()
def flash_attn_unpadded(q, k, v, cu_seqlens_q, cu_seqlens_k, max_seqlen_q,
                        max_seqlen_k, scale=None, dropout=0.0, causal=False,
                        return_softmax=False, is_test=True):
    """Varlen flash-attn: q [total_q, N, H] with cumulative seqlens.

    TPU keeps static shapes: segments are re-packed to a padded batch,
    attended with a mask, and scattered back.
    """
    nq = cu_seqlens_q.shape[0] - 1
    tq, n, h = q.shape
    mq, mk = int(max_seqlen_q), int(max_seqlen_k)  # noqa: H001 (static seqlen attrs)

    def gather_pad(x, cu, m):
        def per(i):
            s = cu[i]
            ln = cu[i + 1] - s
            idx = s + jnp.arange(m)
            valid = jnp.arange(m) < ln
            xi = x[jnp.clip(idx, 0, x.shape[0] - 1)]
            return jnp.where(valid[:, None, None], xi, 0), valid
        return jax.vmap(per)(jnp.arange(nq))

    qp, qv = gather_pad(q, cu_seqlens_q, mq)
    kp, kv = gather_pad(k, cu_seqlens_k, mk)
    vp, _ = gather_pad(v, cu_seqlens_k, mk)
    mask = qv[:, :, None] & kv[:, None, :]  # [B, mq, mk]
    out = _attention_impl(qp, kp, vp, attn_mask=mask[:, None, :, :],
                          is_causal=causal,
                          dropout_p=0.0 if is_test else dropout,
                          scale=scale)

    def scatter_back(o, cu):
        res = jnp.zeros((tq, n, h), o.dtype)

        def body(i, res):
            s = cu[i]
            ln = cu[i + 1] - s
            idx = s + jnp.arange(mq)
            valid = jnp.arange(mq) < ln
            upd = jnp.where(valid[:, None, None], o[i], 0)
            return res.at[jnp.clip(idx, 0, tq - 1)].add(
                jnp.where(valid[:, None, None], upd, 0))
        return jax.lax.fori_loop(0, nq, body, res)

    res = scatter_back(out, cu_seqlens_q)
    if return_softmax:
        return res, None
    return res


@op()
def memory_efficient_attention(query, key, value, bias=None, cu_seqlens_q=None,
                               cu_seqlens_k=None, causal_diagonal=None,
                               seqlen_k=None, max_seqlen_q=None,
                               max_seqlen_k=None, causal=False, dropout_p=0.0,
                               scale=None, is_test=True):
    """Reference: python/paddle/incubate/nn/memory_efficient_attention.py
    (cutlass kernels).  On TPU this is the same flash path."""
    return _attention_impl(query, key, value, attn_mask=bias,
                           is_causal=causal,
                           dropout_p=0.0 if is_test else dropout_p,
                           scale=scale)


@op()
def fused_attention(x, qkv_weight, qkv_bias, linear_weight, linear_bias,
                    ln_scale=None, ln_bias=None, ln2_scale=None,
                    ln2_bias=None, num_heads=1, pre_layer_norm=False,
                    epsilon=1e-5, epsilon2=None, attn_dropout_rate=0.0,
                    dropout_rate=0.0, is_test=True, attn_mask=None,
                    ring_id=-1):
    """fused_attention op parity (paddle/fluid/operators/fused/
    fused_attention_op.cu): [LN] → QKV → MHA → out-proj → residual [→ LN]."""
    b, t, c = x.shape
    h = c // num_heads
    residual = x
    inp = _ln(x, ln_scale, ln_bias, epsilon) if pre_layer_norm else x
    # qkv_weight [3, num_heads, head_dim, C]
    qkv = jnp.einsum("btc,khdc->btkhd",
                     inp.astype(jnp.float32),
                     qkv_weight.astype(jnp.float32))
    if qkv_bias is not None:
        qkv = qkv + qkv_bias.astype(jnp.float32)[None, None]
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # [B,T,N,H]
    ctx = _attention_impl(q.astype(x.dtype), k.astype(x.dtype),
                          v.astype(x.dtype), attn_mask=attn_mask,
                          dropout_p=0.0 if is_test else attn_dropout_rate)
    ctx = ctx.reshape(b, t, c)
    out = ctx.astype(jnp.float32) @ linear_weight.astype(jnp.float32)
    if linear_bias is not None:
        out = out + linear_bias.astype(jnp.float32)
    out = residual.astype(jnp.float32) + out
    if not pre_layer_norm:
        out = _ln(out.astype(x.dtype), ln2_scale, ln2_bias,
                  epsilon if epsilon2 is None else epsilon2) \
            .astype(jnp.float32)
    return out.astype(x.dtype)


@op()
def fused_feedforward(x, linear1_weight, linear1_bias, linear2_weight,
                      linear2_bias, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, pre_layer_norm=False,
                      epsilon1=1e-5, epsilon2=1e-5, act_method="gelu",
                      dropout1_rate=0.0, dropout2_rate=0.0, is_test=True,
                      ring_id=-1):
    residual = x
    inp = _ln(x, ln1_scale, ln1_bias, epsilon1) if pre_layer_norm else x
    h = inp.astype(jnp.float32) @ linear1_weight.astype(jnp.float32)
    if linear1_bias is not None:
        h = h + linear1_bias.astype(jnp.float32)
    act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu}[act_method]
    h = act(h)
    out = h @ linear2_weight.astype(jnp.float32)
    if linear2_bias is not None:
        out = out + linear2_bias.astype(jnp.float32)
    out = residual.astype(jnp.float32) + out
    if not pre_layer_norm:
        out = _ln(out.astype(x.dtype), ln2_scale, ln2_bias, epsilon2) \
            .astype(jnp.float32)
    return out.astype(x.dtype)


@op()
def fused_dropout_add(x, y, p=0.5, is_test=True, mode="upscale_in_train",
                      seed=0, fix_seed=False):
    if is_test or p == 0.0:
        return x + y
    if fix_seed:
        key = jax.random.PRNGKey(seed)
    else:
        from ..framework.random import get_rng_key
        key = get_rng_key()
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    if mode == "upscale_in_train":
        return jnp.where(keep, x / (1.0 - p), 0.0) + y
    return jnp.where(keep, x, 0.0) + y


@op()
def fused_linear_param_grad_add(x, dout, dweight=None, dbias=None,
                                multi_precision=True, has_bias=True):
    """Grad-accumulation fusion for linear layers (main-grad path)."""
    acc_t = jnp.float32 if multi_precision else x.dtype
    dw = jnp.einsum("...i,...o->io", x.astype(acc_t), dout.astype(acc_t))
    if dweight is not None:
        dw = dweight.astype(acc_t) + dw
    outs = [dw]
    if has_bias:
        db = dout.astype(acc_t).reshape(-1, dout.shape[-1]).sum(0)
        if dbias is not None:
            db = dbias.astype(acc_t) + db
        outs.append(db)
    else:
        outs.append(None)
    return tuple(outs)


# ---------------------------------------------------------- xpu-fused set
# The reference ships backend-specific fused ops for its Kunlun backend;
# the TPU build keeps the registry names and lowers each to the XLA
# composition (which fuses at compile time).

@op()
def add_act_xpu(x, y, act_type="relu"):
    acts = {"relu": jax.nn.relu, "gelu": jax.nn.gelu,
            "sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh,
            "linear": lambda v: v}
    return acts[act_type](x + y)


@op()
def fc_xpu(x, w, bias=None, act_type="linear"):
    out = x.astype(jnp.float32) @ w.astype(jnp.float32)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    acts = {"relu": jax.nn.relu, "gelu": jax.nn.gelu,
            "sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh,
            "linear": lambda v: v}
    return acts[act_type](out).astype(x.dtype)


@op()
def conv2d_xpu(x, filter, bias=None, scale_max=None, out_max_in=None,
               strides=(1, 1), paddings=(0, 0), dilations=(1, 1), groups=1,
               act_type="linear"):
    from .registry import raw
    out = raw("conv2d")(x, filter, bias=None, stride=list(strides),
                        padding=list(paddings), dilation=list(dilations),
                        groups=groups)
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    acts = {"relu": jax.nn.relu, "gelu": jax.nn.gelu,
            "sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh,
            "linear": lambda v: v}
    return acts[act_type](out)


@op()
def embedding_with_eltwise_add_xpu(ids_list, tables_list):
    out = None
    for ids, table in zip(ids_list, tables_list):
        e = table[jnp.asarray(ids, jnp.int32)]
        out = e if out is None else out + e
    return out


@op()
def multi_encoder_xpu(x, qkv_weights, qkv_biases, out_weights, out_biases,
                      ffn1_weights, ffn1_biases, ffn2_weights, ffn2_biases,
                      ln1_scales, ln1_biases, ln2_scales, ln2_biases,
                      num_heads=1, attn_mask=None):
    """Stacked transformer encoder (the reference fuses the whole stack for
    XPU inference; here one composition, compiled once)."""
    h = x
    n_layers = len(qkv_weights)
    for i in range(n_layers):
        h = fused_attention.__wrapped__(
            h, qkv_weights[i], qkv_biases[i], out_weights[i], out_biases[i],
            ln_scale=ln1_scales[i], ln_bias=ln1_biases[i],
            num_heads=num_heads, pre_layer_norm=True, attn_mask=attn_mask)
        h = fused_feedforward.__wrapped__(
            h, ffn1_weights[i], ffn1_biases[i], ffn2_weights[i],
            ffn2_biases[i], ln1_scale=ln2_scales[i], ln1_bias=ln2_biases[i],
            pre_layer_norm=True)
    return h


@op()
def fused_multi_transformer_xpu(x, qkv_weights, qkv_biases, out_weights,
                                out_biases, ffn1_weights, ffn1_biases,
                                ffn2_weights, ffn2_biases, ln_scales,
                                ln_biases, ffn_ln_scales, ffn_ln_biases,
                                num_heads=1, attn_mask=None):
    return multi_encoder_xpu.__wrapped__(
        x, qkv_weights, qkv_biases, out_weights, out_biases, ffn1_weights,
        ffn1_biases, ffn2_weights, ffn2_biases, ln_scales, ln_biases,
        ffn_ln_scales, ffn_ln_biases, num_heads=num_heads,
        attn_mask=attn_mask)


@op()
def generate_sequence_xpu(x, axis=-1, dtype=None):
    n = x.shape[axis]
    seq = jnp.arange(n, dtype=dtype or jnp.int64)
    shape = [1] * x.ndim
    shape[axis] = n
    return jnp.broadcast_to(seq.reshape(shape), x.shape)


@op()
def yolo_box_xpu(x, img_size, anchors, class_num, conf_thresh=0.01,
                 downsample_ratio=32, clip_bbox=True, scale_x_y=1.0):
    from .vision_ops import yolo_box
    return yolo_box.__wrapped__(x, img_size, anchors, class_num,
                                conf_thresh=conf_thresh,
                                downsample_ratio=downsample_ratio,
                                clip_bbox=clip_bbox, scale_x_y=scale_x_y)
