"""Sequence ops: RNN family, CTC/RNN-T losses, decoding, framing.

Reference kernels: paddle/phi/kernels/*/rnn_kernel.* (cuDNN RNN),
warpctc (dyn-loaded, paddle/phi/backends/dynload/warpctc.h),
warprnnt, viterbi_decode (paddle/phi/kernels/cpu/viterbi_decode_kernel.cc),
gather_tree, frame/overlap_add (paddle/phi/kernels/*/frame_*).

TPU design: all recurrences are ``lax.scan`` — XLA compiles the scan body
once and the MXU runs the per-step matmuls; CTC uses optax's TPU-tested
implementation; RNN-T is a log-space DP over anti-diagonal wavefronts.
"""

import jax
import jax.numpy as jnp
from jax import lax

from .registry import op

# ------------------------------------------------------------------- RNN

def _lstm_cell(x, h, c, wi, wh, bi, bh):
    g = x @ wi.T + h @ wh.T + bi + bh
    i, f, gg, o = jnp.split(g, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    c_new = f * c + i * jnp.tanh(gg)
    return jnp.tanh(c_new) * o, c_new


def _gru_cell(x, h, wi, wh, bi, bh):
    gi = x @ wi.T + bi
    gh = h @ wh.T + bh
    ri, zi, ni = jnp.split(gi, 3, axis=-1)
    rh, zh, nh = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(ri + rh)
    z = jax.nn.sigmoid(zi + zh)
    n = jnp.tanh(ni + r * nh)
    return (1 - z) * n + z * h


def _simple_cell(x, h, wi, wh, bi, bh, act):
    return act(x @ wi.T + h @ wh.T + bi + bh)


def _run_layer(x, h0, c0, weights, mode, reverse=False):
    """x [T,B,I]; returns (out [T,B,H], h_T, c_T)."""
    wi, wh, bi, bh = weights
    if reverse:
        x = jnp.flip(x, 0)

    if mode == "LSTM":
        def step(carry, xt):
            h, c = carry
            h2, c2 = _lstm_cell(xt, h, c, wi, wh, bi, bh)
            return (h2, c2), h2
        (hT, cT), out = lax.scan(step, (h0, c0), x)
    elif mode == "GRU":
        def step(h, xt):
            h2 = _gru_cell(xt, h, wi, wh, bi, bh)
            return h2, h2
        hT, out = lax.scan(step, h0, x)
        cT = c0
    else:
        act = jnp.tanh if mode == "RNN_TANH" else jax.nn.relu
        def step(h, xt):
            h2 = _simple_cell(xt, h, wi, wh, bi, bh, act)
            return h2, h2
        hT, out = lax.scan(step, h0, x)
        cT = c0
    if reverse:
        out = jnp.flip(out, 0)
    return out, hT, cT


@op()
def rnn(x, pre_state, weight_list, sequence_length=None, dropout_prob=0.0,
        is_bidirec=False, input_size=0, hidden_size=0, num_layers=1,
        mode="LSTM", seed=0, is_test=False):
    """Multi-layer (bi)directional RNN; x [T,B,I] (time-major).

    weight_list layout per layer per direction: [w_ih, w_hh, b_ih, b_hh]
    (cuDNN flat-weight layout in the reference; explicit list here).
    """
    num_dir = 2 if is_bidirec else 1
    h0_all = pre_state[0]  # [L*D, B, H]
    c0_all = pre_state[1] if mode == "LSTM" and len(pre_state) > 1 else \
        jnp.zeros_like(h0_all)
    out = x
    h_finals, c_finals = [], []
    wptr = 0
    for layer in range(num_layers):
        outs_dir = []
        for d in range(num_dir):
            idx = layer * num_dir + d
            w = tuple(weight_list[wptr:wptr + 4])
            wptr += 4
            o, hT, cT = _run_layer(out, h0_all[idx], c0_all[idx], w, mode,
                                   reverse=(d == 1))
            outs_dir.append(o)
            h_finals.append(hT)
            c_finals.append(cT)
        out = (jnp.concatenate(outs_dir, axis=-1) if num_dir == 2
               else outs_dir[0])
    h_out = jnp.stack(h_finals)
    c_out = jnp.stack(c_finals)
    if sequence_length is not None:
        t = out.shape[0]
        mask = (jnp.arange(t)[:, None] <
                jnp.asarray(sequence_length)[None, :])
        out = out * mask[..., None].astype(out.dtype)
    if mode == "LSTM":
        return out, (h_out, c_out)
    return out, (h_out,)


# ------------------------------------------------------------------- CTC

@op()
def warpctc(logits, label, logits_length=None, labels_length=None,
            blank=0, norm_by_times=False):
    """CTC loss. logits [T,B,C] (paddle warpctc layout) or [B,T,C] w/
    lengths; label [B,L]."""
    import optax
    if logits.ndim != 3:
        raise ValueError("warpctc expects rank-3 logits")
    t, b, c = logits.shape
    lg = jnp.transpose(logits, (1, 0, 2)).astype(jnp.float32)  # [B,T,C]
    if logits_length is None:
        logits_length = jnp.full((b,), t, jnp.int32)
    lab = jnp.asarray(label, jnp.int32)
    if labels_length is None:
        labels_length = (lab != blank).sum(-1).astype(jnp.int32)
    tpad = (jnp.arange(t)[None, :] >=
            jnp.asarray(logits_length)[:, None]).astype(jnp.float32)
    lpad = (jnp.arange(lab.shape[1])[None, :] >=
            jnp.asarray(labels_length)[:, None]).astype(jnp.float32)
    loss = optax.ctc_loss(lg, tpad, lab, lpad, blank_id=blank)
    if norm_by_times:
        loss = loss / jnp.maximum(jnp.asarray(logits_length, jnp.float32), 1)
    return loss


# ----------------------------------------------------------------- RNN-T

@op()
def warprnnt(logits, label, input_lengths, label_lengths, blank=0,
             fastemit_lambda=0.0):
    """RNN-T loss, log-space DP.  logits [B, T, U+1, C]; label [B, U]."""
    b, t, u1, c = logits.shape
    lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    lab = jnp.asarray(label, jnp.int32)
    tl = jnp.asarray(input_lengths, jnp.int32)
    ul = jnp.asarray(label_lengths, jnp.int32)

    blank_lp = lp[:, :, :, blank]  # [B,T,U+1]
    u_idx = jnp.arange(u1 - 1)
    emit_lp = jnp.take_along_axis(
        lp[:, :, :-1, :], lab[:, None, :, None], axis=-1)[..., 0]  # [B,T,U]

    neg_inf = -1e30

    def per_example(blp, elp, tlen, ulen):
        # alpha [T, U+1]; row t from row t-1:
        #   alpha[t, u] = logaddexp(alpha[t-1, u] + blank[t-1, u],
        #                           alpha[t,   u-1] + emit[t, u-1])
        # t = 0 row: alpha[0,0]=0; alpha[0,u]=sum emit[0,:u]
        a0 = jnp.concatenate([jnp.zeros((1,)),
                              jnp.cumsum(elp[0])])

        def t_step(alpha_prev, inp):
            blp_t, elp_t = inp
            from_top = alpha_prev + blp_t
            def scan_u(carry, z):
                ft, e = z
                val = jnp.logaddexp(ft, carry + e)
                return val, val
            init = from_top[0]
            _, rest = lax.scan(scan_u, init, (from_top[1:], elp_t))
            alpha_t = jnp.concatenate([init[None], rest])
            return alpha_t, alpha_t

        _, alpha_rows = lax.scan(t_step, a0, (blp[:-1], elp[1:]))
        alpha = jnp.concatenate([a0[None], alpha_rows], axis=0)  # [T,U+1]
        final = alpha[tlen - 1, ulen] + blp[tlen - 1, ulen]
        return -final

    loss = jax.vmap(per_example)(blank_lp, emit_lp, tl, ul)
    return loss


# --------------------------------------------------------------- decoding

@op()
def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True):
    """CRF Viterbi. potentials [B,T,N], transition [N+2,N+2] if bos/eos."""
    pot = potentials.astype(jnp.float32)
    trans = transition_params.astype(jnp.float32)
    b, t, n = pot.shape
    if include_bos_eos_tag:
        # rows/cols n..n+1 are BOS/EOS in paddle's layout ([N+2,N+2]);
        # here transition is [N,N] core + start/stop vectors when provided
        if trans.shape[0] == n + 2:
            start = trans[n, :n]
            stop = trans[:n, n + 1]
            core = trans[:n, :n]
        else:
            start = jnp.zeros((n,))
            stop = jnp.zeros((n,))
            core = trans
    else:
        start = jnp.zeros((n,))
        stop = jnp.zeros((n,))
        core = trans

    def per_seq(p, ln):
        alpha0 = p[0] + start
        # mask steps beyond length: freeze alpha after ln-1
        valid = jnp.arange(1, t) < ln

        def masked_step(alpha, inp):
            pt, v = inp
            scores = alpha[:, None] + core
            bp = jnp.argmax(scores, axis=0)
            alpha_new = jnp.max(scores, axis=0) + pt
            alpha_out = jnp.where(v, alpha_new, alpha)
            bp_out = jnp.where(v, bp, jnp.arange(n))
            return alpha_out, bp_out

        alphaT, backptrs = lax.scan(masked_step, alpha0, (p[1:], valid))
        alphaT = alphaT + (stop if include_bos_eos_tag else 0.0)
        best_last = jnp.argmax(alphaT)
        score = jnp.max(alphaT)

        def back_step(tag, bp):
            prev = bp[tag]
            # emit PREV (the tag at step t-1), not the carried tag:
            # emitting the carry drops path[0] and duplicates the final
            # tag (caught by the round-3 numpy Viterbi reference)
            return prev, prev

        _, path_rev = lax.scan(back_step, best_last,
                               jnp.flip(backptrs, 0))
        path = jnp.concatenate([jnp.flip(path_rev), best_last[None]])
        return score, path.astype(jnp.int64)

    scores, paths = jax.vmap(per_seq)(pot, jnp.asarray(lengths, jnp.int32))
    return scores, paths


@op()
def gather_tree(ids, parents):
    """Beam-search backtrace. ids/parents [T, B, W] → full paths."""
    t, b, w = ids.shape

    def per_batch(idb, parb):  # [T,W]
        def step(beam_idx, inp):
            idt, part = inp  # each [W]
            tok = idt[beam_idx]
            prev = part[beam_idx]
            return prev, tok

        last = jnp.arange(w)
        _, toks = lax.scan(step, last, (jnp.flip(idb, 0),
                                        jnp.flip(parb, 0)))
        return jnp.flip(toks, 0)

    out = jax.vmap(per_batch, in_axes=1, out_axes=1)(ids, parents)
    return out


@op()
def edit_distance(hyps, refs, hypslength=None, refslength=None,
                  normalized=True):
    """Levenshtein distance per pair; hyps/refs [B, L] padded int."""
    b, lh = hyps.shape
    lr = refs.shape[1]
    if hypslength is None:
        hypslength = jnp.full((b,), lh, jnp.int32)
    if refslength is None:
        refslength = jnp.full((b,), lr, jnp.int32)

    def per_pair(h, r, hl, rl):
        row0 = jnp.arange(lr + 1, dtype=jnp.int32)

        def step(prev_row, i):
            hi = h[i]

            def col(carry, j):
                left = carry  # dp[i+1][j]
                diag = prev_row[j]
                up = prev_row[j + 1]
                cost = jnp.where(hi == r[j], 0, 1)
                val = jnp.minimum(jnp.minimum(up + 1, left + 1), diag + cost)
                # past ref length: keep propagating minimal value
                return val, val

            first = prev_row[0] + 1
            _, rest = lax.scan(col, first, jnp.arange(lr))
            new_row = jnp.concatenate([first[None], rest])
            # rows past hyp length: carry previous row through
            new_row = jnp.where(i < hl, new_row, prev_row)
            return new_row, None

        final_row, _ = lax.scan(step, row0, jnp.arange(lh))
        d = final_row[rl]
        if normalized:
            return d.astype(jnp.float32) / jnp.maximum(
                rl.astype(jnp.float32), 1.0)
        return d.astype(jnp.float32)

    dist = jax.vmap(per_pair)(jnp.asarray(hyps), jnp.asarray(refs),
                              jnp.asarray(hypslength, jnp.int32),
                              jnp.asarray(refslength, jnp.int32))
    return dist.reshape(b, 1), jnp.asarray([b], jnp.int64)


# ------------------------------------------------------------ stft helpers

@op()
def frame(x, frame_length, hop_length, axis=-1):
    """Slice overlapping frames along ``axis``."""
    if axis in (-1, x.ndim - 1):
        n = x.shape[-1]
        n_frames = 1 + (n - frame_length) // hop_length
        idx = (jnp.arange(n_frames)[:, None] * hop_length
               + jnp.arange(frame_length)[None, :])
        out = x[..., idx]  # [..., n_frames, frame_length]
        return jnp.swapaxes(out, -1, -2)  # [..., frame_length, n_frames]
    # axis == 0
    n = x.shape[0]
    n_frames = 1 + (n - frame_length) // hop_length
    idx = (jnp.arange(n_frames)[None, :] * hop_length
           + jnp.arange(frame_length)[:, None])
    return x[idx]  # [frame_length, n_frames, ...]


@op()
def overlap_add(x, hop_length, axis=-1):
    """Inverse of frame: x [..., frame_length, n_frames] → signal."""
    if axis in (-1, x.ndim - 1):
        xt = jnp.swapaxes(x, -1, -2)  # [..., n_frames, frame_length]
        n_frames, frame_length = xt.shape[-2], xt.shape[-1]
        out_len = (n_frames - 1) * hop_length + frame_length
        lead = xt.shape[:-2]
        flat = xt.reshape((-1, n_frames, frame_length))

        def per(sig):
            o = jnp.zeros((out_len,), x.dtype)
            idx = (jnp.arange(n_frames)[:, None] * hop_length
                   + jnp.arange(frame_length)[None, :])
            return o.at[idx.reshape(-1)].add(sig.reshape(-1))

        out = jax.vmap(per)(flat)
        return out.reshape(lead + (out_len,))
    # axis == 0: x is [frame_length, n_frames, ...]
    xt = jnp.moveaxis(x, (0, 1), (-1, -2))  # [..., n_frames, frame_length]
    res = overlap_add.__wrapped__(jnp.swapaxes(xt, -1, -2), hop_length,
                                  axis=-1)
    return jnp.moveaxis(res, -1, 0)
