"""Pallas decode attention — single-token query over a ragged KV cache.

The generative-decode hot loop (reference analog: the masked attention
inside fused_multi_transformer_op.cu's decode branch).  Shapes:

    q        [B, Nq, D]          one new token per sequence
    k_cache  [B, S_max, Nkv, D]  Nq % Nkv == 0 (GQA: G = Nq//Nkv query
    v_cache  [B, S_max, Nkv, D]  heads share one KV head)
    lengths  [B] int32           valid cache prefix per sequence

Kernel layout: one program per (batch, kv_head); the program streams the
KV cache in S-blocks from VMEM, computing all G grouped query heads at
once ([G, D] @ [D, S_blk] rides the MXU), with an online softmax across
blocks and per-position masking by ``lengths`` — ragged sequences cost
only their occupied blocks' bandwidth, never S_max compute on the VPU
path.

TPU-shape constraints: D <= 128, S_max % block_s == 0.  ``supports``
gates callers; the XLA fallback (used by FusedMultiTransformer by
default) computes the same masked attention densely.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from . import registry

DEFAULT_BLOCK_S = 128
_NEG_INF = -1e30


def _pick_block(s_max, preferred=DEFAULT_BLOCK_S):
    from . import pick_block

    return pick_block(s_max, preferred,
                      candidates=(256, 128, 64, 32, 16, 8))


def supports(s_max, head_dim, num_q_heads, num_kv_heads):
    return (head_dim <= 128 and _pick_block(s_max) is not None
            and num_q_heads % num_kv_heads == 0)


def _decode_kernel(q_ref, k_ref, v_ref, len_ref, o_ref, *, block_s):
    """One (batch, kv_head) program: G query heads over the KV prefix."""
    q = q_ref[0, :, 0, :].astype(jnp.float32)          # [G, D]
    s_max = k_ref.shape[1]
    g, d = q.shape
    length = len_ref[0]

    def body(i, carry):
        o, m, l = carry
        k = k_ref[0, pl.ds(i * block_s, block_s), 0, :] \
            .astype(jnp.float32)                        # [S, D]
        v = v_ref[0, pl.ds(i * block_s, block_s), 0, :] \
            .astype(jnp.float32)                        # [S, D]
        s = q @ k.T / jnp.sqrt(jnp.float32(d))          # [G, S]
        pos = i * block_s + jax.lax.broadcasted_iota(
            jnp.int32, (g, block_s), 1)
        s = jnp.where(pos < length, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                          # [G, S]
        alpha = jnp.exp(m - m_new)
        o = o * alpha + p @ v                           # [G, D]
        l = l * alpha[:, 0] + p.sum(axis=1)
        return o, m_new, l

    num_blocks = s_max // block_s
    o0 = jnp.zeros((g, d), jnp.float32)
    m0 = jnp.full((g, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((g,), jnp.float32)
    o, m, l = jax.lax.fori_loop(0, num_blocks, body, (o0, m0, l0))
    # lengths[b] == 0: every position is masked, the running max collapses
    # to the mask value so p == 1 everywhere and o/l silently averages the
    # whole (uninitialized) cache — emit zeros for empty sequences instead
    safe = jnp.where(length > 0, o / jnp.maximum(l[:, None], 1e-30), 0.0)
    o_ref[0, :, 0, :] = safe.astype(o_ref.dtype)


def _engine_cases(engine):
    """Dense-cache decode at power-of-two batch buckets up to the
    engine's max_batch (S_max is the paged pool's token horizon,
    per-shard head counts under tp).  The serving engine's own bucket
    grid is a single ragged-token family now, so the batch buckets are
    enumerated directly here rather than read off ``_bucket_grid()``."""
    nkv = max(engine.num_heads // engine.tp, 1)
    d = engine.head_dim
    s_max = engine.max_pages * engine.block_size
    if not supports(s_max, d, nkv, nkv):
        return
    sds = jax.ShapeDtypeStruct
    bkt = 1
    while True:
        q = sds((bkt, nkv, d), engine.dtype)
        kc = sds((bkt, s_max, nkv, d), engine.dtype)
        yield registry.KernelCase(
            f"decode[{bkt}]", decode_attention_pallas,
            (q, kc, kc, sds((bkt,), jnp.int32)), None)
        if bkt >= engine.max_batch:
            break
        bkt = min(bkt * 2, engine.max_batch)


@registry.register_kernel(
    "decode_attention",
    fallback="paddle_tpu.ops.pallas.decode_attention_kernel:"
             "decode_attention_xla",
    parity="tests/test_pallas_kernels.py::TestDecodeAttention::"
           "test_matches_xla_reference_ragged_gqa",
    engine_shapes=_engine_cases,
    supports=supports)
def decode_attention_pallas(q, k_cache, v_cache, lengths, block_s=None,
                            interpret=False):
    """Returns [B, Nq, D] attention outputs for one decode step."""
    b, nq, d = q.shape
    s_max, nkv = k_cache.shape[1], k_cache.shape[2]
    g = nq // nkv
    block_s = block_s or _pick_block(s_max)
    # regroup query heads by their kv head: [B, Nkv, G, D]
    qg = q.reshape(b, nkv, g, d)
    lengths = lengths.astype(jnp.int32)

    grid = (b, nkv)
    out = pl.pallas_call(
        functools.partial(_decode_kernel, block_s=block_s),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, g, 1, d), lambda i, j: (i, 0, j, 0)),
            pl.BlockSpec((1, s_max, 1, d), lambda i, j: (i, 0, j, 0)),
            pl.BlockSpec((1, s_max, 1, d), lambda i, j: (i, 0, j, 0)),
            pl.BlockSpec((1,), lambda i, j: (i,)),
        ],
        out_specs=pl.BlockSpec((1, g, 1, d), lambda i, j: (i, 0, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b, g, nkv, d), q.dtype),
        interpret=interpret,
    )(qg.transpose(0, 2, 1, 3), k_cache, v_cache, lengths)
    # out [B, G, Nkv, D] -> [B, Nq, D]
    return out.transpose(0, 2, 1, 3).reshape(b, nq, d)


def decode_attention_xla(q, k_cache, v_cache, lengths):
    """Dense masked reference/fallback (same semantics)."""
    b, nq, d = q.shape
    s_max, nkv = k_cache.shape[1], k_cache.shape[2]
    g = nq // nkv
    qg = q.reshape(b, nkv, g, d)
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    logits = jnp.einsum("bngd,bsnd->bngs", qg.astype(jnp.float32),
                        k_cache.astype(jnp.float32)) * scale
    mask = jnp.arange(s_max)[None, None, None, :] < \
        lengths[:, None, None, None]
    logits = jnp.where(mask, logits, _NEG_INF)
    p = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bngs,bsnd->bngd", p,
                     v_cache.astype(jnp.float32))
    # empty sequences: the all-masked softmax degenerates to a uniform
    # average over the cache — zero those rows (matches the Pallas kernel)
    out = jnp.where(lengths[:, None, None, None] > 0, out, 0.0)
    return out.reshape(b, nq, d).astype(q.dtype)
