"""Pallas paged decode attention — block-table indirection over a paged KV
cache (Ragged Paged Attention, arxiv 2604.15464).

The round-4 ragged decode kernel (decode_attention_kernel.py) reads a
DENSE per-sequence cache [B, S_max, Nkv, D]; a continuous-batching server
cannot afford that layout — sequences join and leave the batch every step,
so the cache is carved into fixed-size token pages owned by a free-list
allocator (inference/llm/block_manager.py) and each sequence sees the
cache through its block table.  Shapes:

    q             [B, Nq, D]      one new token per sequence (GQA:
                                  G = Nq//Nkv query heads per KV head)
    k_pages       [NB, bs, Nkv, D] the whole paged pool, NB pages of
    v_pages       [NB, bs, Nkv, D] bs tokens each
    block_tables  [B, P] int32    page id of each sequence's p-th page
    lengths       [B]    int32    tokens valid per sequence (ctx length)

Kernel layout: grid (B, Nkv, P) with the block tables and lengths as
scalar-prefetch operands, so the BlockSpec index map dereferences
``block_tables[b, p]`` to DMA exactly the pages a sequence owns — the
pool itself never moves.  Online softmax accumulates across the P pages
in VMEM scratch (the grid's innermost axis runs sequentially per (b, h)),
and positions >= lengths[b] are masked, so a 7-token sequence in a
4096-token pool costs one page of bandwidth, not the pool.

Like the ragged kernel, the 1/sqrt(D) scale is applied INSIDE (callers
pre-scale q if their formula differs); ``supports`` gates callers and the
masked-XLA gather fallback (inference/llm/paged_attention.py) computes
identical semantics everywhere else.

Under tensor parallelism the pool is sharded along the Nkv axis and the
kernel runs inside ``jax.shard_map`` with PER-SHARD head counts (Nkv/mp
KV heads, Nq/mp query heads) and the full local pool — nothing here
changes: the grid simply spans fewer kv heads per device, and the
scalar-prefetched block tables (which GSPMD could not partition through
the index map) arrive replicated, indexing local pages.  ``supports``
is consulted with the per-shard counts, so GQA divisibility must hold
per shard, not just globally.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import registry

_NEG_INF = -1e30


def supports(block_size, head_dim, num_q_heads, num_kv_heads):
    return (head_dim <= 128 and block_size % 8 == 0
            and num_q_heads % num_kv_heads == 0)


def _paged_kernel(bt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                  o_scr, m_scr, l_scr, *, block_size):
    """One (batch, kv_head, page) program; scratch carries the online
    softmax state across the page axis."""
    b = pl.program_id(0)
    p = pl.program_id(2)
    num_pages = pl.num_programs(2)
    g, d = q_ref.shape[2], q_ref.shape[3]
    length = len_ref[b]

    @pl.when(p == 0)
    def _init():
        o_scr[...] = jnp.zeros_like(o_scr)
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)

    base = p * block_size

    @pl.when(base < length)
    def _accumulate():
        q = q_ref[0, 0].astype(jnp.float32)                 # [G, D]
        k = k_ref[0, :, 0, :].astype(jnp.float32)           # [bs, D]
        v = v_ref[0, :, 0, :].astype(jnp.float32)           # [bs, D]
        s = q @ k.T / jnp.sqrt(jnp.asarray(d, jnp.float32))  # [G, bs]
        pos = base + jax.lax.broadcasted_iota(jnp.int32, (g, block_size), 1)
        s = jnp.where(pos < length, s, _NEG_INF)
        m_prev, l_prev, o_prev = m_scr[...], l_scr[...], o_scr[...]
        m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        pe = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        o_scr[...] = o_prev * alpha + pe @ v
        m_scr[...] = m_new
        l_scr[...] = l_prev * alpha + pe.sum(axis=1, keepdims=True)

    @pl.when(p == num_pages - 1)
    def _finalize():
        # lengths[b] == 0 (a padded batch slot): everything was masked —
        # emit zeros instead of 0/0 over whatever the pool pages hold
        safe = jnp.where(length > 0,
                         o_scr[...] / jnp.maximum(l_scr[...], 1e-30), 0.0)
        o_ref[0, 0] = safe.astype(o_ref.dtype)


def _decode_engine_cases(engine):
    """Every decode/verify launch of the paged kernel: decode buckets
    are [B] single-token rows, verify buckets flatten to Bb*(Kb+1)
    rows; block-table entries are page ids in [0, num_blocks - 1] (the
    scalar_bounds K003 needs to prove the prefetch indirection safe)."""
    nkv = max(engine.num_heads // engine.tp, 1)
    d = engine.head_dim
    if not supports(engine.block_size, d, nkv, nkv):
        return
    sds = jax.ShapeDtypeStruct
    kp = sds((engine.num_blocks, engine.block_size, nkv, d),
             engine.dtype)
    bounds = {0: (0, engine.num_blocks - 1),
              1: (0, engine.max_model_len)}
    for kind, bkt in engine._bucket_grid():
        if kind == "decode":
            rows, label = bkt, f"decode[{bkt}]"
        elif kind == "verify":
            bb, kb = bkt
            rows, label = bb * (kb + 1), f"verify[{bkt}]"
        else:
            continue
        yield registry.KernelCase(
            label, paged_decode_attention_pallas,
            (sds((rows, nkv, d), engine.dtype), kp, kp,
             sds((rows, engine.max_pages), jnp.int32),
             sds((rows,), jnp.int32)), bounds)


@registry.register_kernel(
    "paged_decode_attention",
    fallback="paddle_tpu.inference.llm.paged_attention:"
             "paged_decode_attention_xla",
    parity="tests/test_pallas_kernels.py::TestPagedAttention::"
           "test_decode_parity_ragged_gqa",
    engine_shapes=_decode_engine_cases,
    supports=supports)
def paged_decode_attention_pallas(q, k_pages, v_pages, block_tables,
                                  lengths, interpret=False):
    """Returns [B, Nq, D] attention outputs for one paged decode step."""
    b, nq, d = q.shape
    _, bs, nkv, _ = k_pages.shape
    g = nq // nkv
    num_pages = block_tables.shape[1]
    qg = q.reshape(b, nkv, g, d)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, nkv, num_pages),
        in_specs=[
            pl.BlockSpec((1, 1, g, d), lambda i, j, p, bt, ln: (i, j, 0, 0)),
            pl.BlockSpec((1, bs, 1, d),
                         lambda i, j, p, bt, ln: (bt[i, p], 0, j, 0)),
            pl.BlockSpec((1, bs, 1, d),
                         lambda i, j, p, bt, ln: (bt[i, p], 0, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, g, d),
                               lambda i, j, p, bt, ln: (i, j, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((g, d), jnp.float32),
            pltpu.VMEM((g, 1), jnp.float32),
            pltpu.VMEM((g, 1), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_kernel, block_size=bs),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, nkv, g, d), q.dtype),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), lengths.astype(jnp.int32),
      qg, k_pages, v_pages)
    return out.reshape(b, nq, d)


# --------------------------------------------------------------- prefill --
def prefill_supports(block_size, head_dim, num_q_heads, num_kv_heads,
                     chunk):
    """Chunked-prefill kernel constraints: on top of the decode gates,
    the [C*G, D] query tile must satisfy the f32 (8, 128) minimum."""
    if not supports(block_size, head_dim, num_q_heads, num_kv_heads):
        return False
    g = num_q_heads // num_kv_heads
    return (chunk * g) % 8 == 0


def _prefill_kernel(bt_ref, meta_ref, q_ref, k_ref, v_ref, o_ref,
                    o_scr, m_scr, l_scr, *, block_size, group):
    """One (kv_head, page) program for ONE sequence's prefill chunk.

    The chunk's C queries sit at absolute positions start..start+C-1
    (``start`` rides in as a scalar-prefetch operand so it can be a
    traced value under jit); row r of the [C*G, D] query tile belongs to
    query index r // group, and the causal mask admits key position
    k iff k <= start + r // group.  Pages past the chunk's last query
    hold no visible keys and are skipped outright.
    """
    p = pl.program_id(1)
    num_pages = pl.num_programs(1)
    cg, d = q_ref.shape[1], q_ref.shape[2]
    start = meta_ref[0]

    @pl.when(p == 0)
    def _init():
        o_scr[...] = jnp.zeros_like(o_scr)
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)

    base = p * block_size

    @pl.when(base < start + cg // group)
    def _accumulate():
        q = q_ref[0].astype(jnp.float32)                    # [CG, D]
        k = k_ref[0, :, 0, :].astype(jnp.float32)           # [bs, D]
        v = v_ref[0, :, 0, :].astype(jnp.float32)           # [bs, D]
        s = q @ k.T / jnp.sqrt(jnp.asarray(d, jnp.float32))  # [CG, bs]
        kpos = base + jax.lax.broadcasted_iota(
            jnp.int32, (cg, block_size), 1)
        qpos = start + jax.lax.broadcasted_iota(
            jnp.int32, (cg, block_size), 0) // group
        s = jnp.where(kpos <= qpos, s, _NEG_INF)
        m_prev, l_prev, o_prev = m_scr[...], l_scr[...], o_scr[...]
        m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        pe = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        o_scr[...] = o_prev * alpha + pe @ v
        m_scr[...] = m_new
        l_scr[...] = l_prev * alpha + pe.sum(axis=1, keepdims=True)

    @pl.when(p == num_pages - 1)
    def _finalize():
        # every row sees at least key position 0 (qpos >= start >= 0),
        # so l > 0 always; the maximum is belt-and-braces
        o_ref[0] = (o_scr[...]
                    / jnp.maximum(l_scr[...], 1e-30)).astype(o_ref.dtype)


def _prefill_engine_cases(engine):
    """Every chunked-prefill launch: one case per chunk bucket (start
    rides in as a traced scalar, bounded by the model horizon)."""
    nkv = max(engine.num_heads // engine.tp, 1)
    d = engine.head_dim
    sds = jax.ShapeDtypeStruct
    kp = sds((engine.num_blocks, engine.block_size, nkv, d),
             engine.dtype)
    bounds = {0: (0, engine.num_blocks - 1),
              1: (0, engine.max_model_len - 1)}
    for kind, bkt in engine._bucket_grid():
        if kind != "chunk":
            continue
        if not prefill_supports(engine.block_size, d, nkv, nkv, bkt):
            continue
        yield registry.KernelCase(
            f"chunk[{bkt}]", paged_prefill_attention_pallas,
            (sds((1, bkt, nkv, d), engine.dtype), kp, kp,
             sds((engine.max_pages,), jnp.int32),
             sds((), jnp.int32)), bounds)


@registry.register_kernel(
    "paged_prefill_attention",
    fallback="paddle_tpu.inference.llm.paged_attention:"
             "paged_prefill_attention_xla",
    parity="tests/test_pallas_kernels.py::TestPagedAttention::"
           "test_prefill_parity_partial_page",
    engine_shapes=_prefill_engine_cases,
    supports=prefill_supports)
def paged_prefill_attention_pallas(q, k_pages, v_pages, block_table,
                                   start, interpret=False):
    """Causal attention for one sequence's prefill chunk through its
    block table.

    q [1, C, Nq, D] at absolute positions start..start+C-1 (K/V for the
    chunk itself already scattered into the pool); returns
    [1, C, Nq, D].  Grid (Nkv, P) with the page axis innermost so the
    VMEM scratch carries the online softmax across the sequence's pages.
    """
    _, c, nq, d = q.shape
    _, bs, nkv, _ = k_pages.shape
    g = nq // nkv
    num_pages = block_table.shape[0]
    # [C, Nkv, G, D] -> [Nkv, C*G, D]: row r of head j is query r // G
    qg = q[0].reshape(c, nkv, g, d).transpose(1, 0, 2, 3)
    qg = qg.reshape(nkv, c * g, d)
    meta = jnp.asarray(start, jnp.int32).reshape(1)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(nkv, num_pages),
        in_specs=[
            pl.BlockSpec((1, c * g, d), lambda j, p, bt, mt: (j, 0, 0)),
            pl.BlockSpec((1, bs, 1, d),
                         lambda j, p, bt, mt: (bt[p], 0, j, 0)),
            pl.BlockSpec((1, bs, 1, d),
                         lambda j, p, bt, mt: (bt[p], 0, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, c * g, d),
                               lambda j, p, bt, mt: (j, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((c * g, d), jnp.float32),
            pltpu.VMEM((c * g, 1), jnp.float32),
            pltpu.VMEM((c * g, 1), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_prefill_kernel, block_size=bs, group=g),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((nkv, c * g, d), q.dtype),
        interpret=interpret,
    )(block_table.astype(jnp.int32), meta, qg, k_pages, v_pages)
    return out.reshape(nkv, c, g, d).transpose(1, 0, 2, 3).reshape(
        1, c, nq, d)
