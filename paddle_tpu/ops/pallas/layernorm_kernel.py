"""Fused LayerNorm as a Pallas TPU kernel (forward + backward).

The reference fuses layernorm into residual/dropout chains with hand-written
CUDA (paddle/phi/kernels/fusion/gpu/fused_layernorm_*); XLA already fuses
most of this, so the Pallas kernel targets the remaining win: one pass over
HBM computing mean/rstd and the normalized output per row block, with a
recompute-free backward that reads the saved statistics.

Layout: input reshaped to [rows, C]; grid over row blocks; C (the feature
dim) must be lane-aligned (multiple of 128) for the kernel path, else the
caller falls back to the XLA composition.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from . import registry

_ROW_BLOCK = 256


def _pick_rows(rows):
    for b in (_ROW_BLOCK, 128, 64, 32, 16, 8):
        if rows % b == 0:
            return b
    return None


def supports(rows, channels):
    return channels % 128 == 0 and _pick_rows(rows) is not None


def _fwd_kernel(x_ref, g_ref, b_ref, y_ref, mu_ref, rstd_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)                   # [BR, C]
    mu = jnp.mean(x, axis=1, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = xc * rstd * g_ref[...].astype(jnp.float32) + b_ref[...].astype(
        jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)
    mu_ref[...] = mu
    rstd_ref[...] = rstd


def _bwd_kernel(x_ref, g_ref, mu_ref, rstd_ref, dy_ref, dx_ref, dg_ref,
                db_ref):
    x = x_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    mu = mu_ref[...]
    rstd = rstd_ref[...]
    xhat = (x - mu) * rstd
    wdy = dy * g
    c1 = jnp.mean(wdy, axis=1, keepdims=True)
    c2 = jnp.mean(wdy * xhat, axis=1, keepdims=True)
    dx_ref[...] = ((wdy - c1 - xhat * c2) * rstd).astype(dx_ref.dtype)
    # dg/db accumulate across the (sequential) TPU grid into one [1, C]
    # block — a [nb, C] partials array would need a block whose leading dim
    # is 1, which the TPU lowering rejects for nb not divisible by 8.
    @pl.when(pl.program_id(0) == 0)
    def _init():
        dg_ref[...] = jnp.zeros_like(dg_ref)
        db_ref[...] = jnp.zeros_like(db_ref)

    dg_ref[...] += jnp.sum(dy * xhat, axis=0, keepdims=True)
    db_ref[...] += jnp.sum(dy, axis=0, keepdims=True)


def _ln_fwd(x2d, g, b, eps, block_rows, interpret):
    rows, c = x2d.shape
    grid = (rows // block_rows,)
    y, mu, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, c), lambda i: (i, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, c), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, c), x2d.dtype),
            jax.ShapeDtypeStruct((rows, 1), jnp.float32),
            jax.ShapeDtypeStruct((rows, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x2d, g.reshape(1, c), b.reshape(1, c))
    return y, mu, rstd


def _ln_bwd(x2d, g, mu, rstd, dy, block_rows, interpret):
    rows, c = x2d.shape
    nb = rows // block_rows
    dx, dgp, dbp = pl.pallas_call(
        _bwd_kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((block_rows, c), lambda i: (i, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, c), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, c), lambda i: (i, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, c), x2d.dtype),
            jax.ShapeDtypeStruct((1, c), jnp.float32),
            jax.ShapeDtypeStruct((1, c), jnp.float32),
        ],
        interpret=interpret,
    )(x2d, g.reshape(1, c), mu, rstd, dy)
    return dx, dgp[0], dbp[0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _layernorm2d(x2d, g, b, eps, interpret):
    y, _, _ = _ln_fwd(x2d, g, b, eps, _pick_rows(x2d.shape[0]), interpret)
    return y


def _layernorm2d_fwd(x2d, g, b, eps, interpret):
    y, mu, rstd = _ln_fwd(x2d, g, b, eps, _pick_rows(x2d.shape[0]), interpret)
    return y, (x2d, g, mu, rstd)


def _layernorm2d_bwd(eps, interpret, res, dy):
    x2d, g, mu, rstd = res
    dx, dg, db = _ln_bwd(x2d, g, mu, rstd, dy, _pick_rows(x2d.shape[0]),
                         interpret)
    return dx, dg.astype(g.dtype), db.astype(g.dtype)


_layernorm2d.defvjp(_layernorm2d_fwd, _layernorm2d_bwd)


def _engine_cases(engine):
    """Tiny test engines sit below the 128-lane channel minimum (the
    kernel is gated off there), so fall back to the smallest supported
    multi-block envelope — the sweep must always exercise the fwd AND
    bwd kernels, including the dg/db cross-grid accumulation K004
    deliberately admits."""
    rows, c = engine.token_budget, engine.hidden
    if not supports(rows, c):
        rows, c = 512, 128
    sds = jax.ShapeDtypeStruct
    x = sds((rows, c), jnp.float32)
    w = sds((c,), jnp.float32)

    def fwd(x, g, b):
        return layernorm_pallas(x, g, b)

    def vjp(x, g, b):
        def loss(*a):
            return jnp.sum(layernorm_pallas(*a).astype(jnp.float32))
        return jax.grad(loss, argnums=(0, 1, 2))(x, g, b)

    yield registry.KernelCase(f"fwd[{rows}x{c}]", fwd, (x, w, w), None)
    yield registry.KernelCase(f"vjp[{rows}x{c}]", vjp, (x, w, w), None)


@registry.register_kernel(
    "layernorm",
    fallback="paddle_tpu.nn.functional:layer_norm",
    parity="tests/test_pallas_kernels.py::test_layernorm_forward_and_grads",
    engine_shapes=_engine_cases,
    supports=supports,
    grad=True)
def layernorm_pallas(x, gamma, beta, eps=1e-5, interpret=False):
    """LayerNorm over the last dim; x any rank, gamma/beta shape [C]."""
    c = x.shape[-1]
    rows = 1
    for d in x.shape[:-1]:
        rows *= d
    y = _layernorm2d(x.reshape(rows, c), gamma, beta, float(eps), interpret)
    return y.reshape(x.shape)
