"""Flash attention as a Pallas TPU kernel (forward + backward).

The reference ships FlashAttention as a dyn-loaded CUDA library
(paddle/phi/kernels/gpu/flash_attn_kernel.cu, loader
paddle/phi/backends/dynload/flashattn.h).  Here the kernel is written
TPU-native in Pallas: online-softmax over key blocks (never materializes the
[T, T] score matrix), fp32 accumulation feeding the MXU, and a
recompute-based backward (dq and dk/dv as separate kernels), wired up as a
jax.custom_vjp.

Layouts: paddle's flash-attn API is [batch, seq, num_heads, head_dim]
(python/paddle/nn/functional/flash_attention.py:125); kernels run on
[batch*heads, seq, head_dim].

Constraints (else the caller falls back to the XLA composition): seq divisible
by the block size, head_dim <= 128.  Attention dropout and additive masks use
the fallback path.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import registry

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
_NEG_INF = -1e30


def _pick_block(seq, preferred):
    from . import pick_block

    return pick_block(seq, preferred)


def supports(seq_q, seq_k, head_dim):
    return (head_dim <= 128
            and _pick_block(seq_q, DEFAULT_BLOCK_Q) is not None
            and _pick_block(seq_k, DEFAULT_BLOCK_K) is not None)


# ---------------------------------------------------------------- forward --

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k, causal,
                scale):
    """One (batch*head, q-block) program: online softmax over key blocks."""
    q = q_ref[0].astype(jnp.float32) * scale          # [Bq, H]
    block_q = q.shape[0]
    seq_k = k_ref.shape[1]
    num_kb = seq_k // block_k
    qi = pl.program_id(1)

    def body(j, carry):
        o_acc, m, l = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # [Bq,Bk]
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        o_new = o_acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return o_new, m_new, l_new

    o0 = jnp.zeros((block_q, q.shape[1]), jnp.float32)
    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    if causal:
        # key blocks beyond this q block's diagonal are fully masked
        upper = (qi + 1) * block_q
        num_active = (upper + block_k - 1) // block_k
        o_acc, m, l = jax.lax.fori_loop(0, num_active, body, (o0, m0, l0))
    else:
        o_acc, m, l = jax.lax.fori_loop(0, num_kb, body, (o0, m0, l0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0] = (o_acc / l).astype(o_ref.dtype)
    # lse is [bn, seq, 1]: a (1, block_q, 1) block per program satisfies the
    # Mosaic tile constraint (trailing dim equals the full array dim).
    lse_ref[0] = m + jnp.log(l)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    bn, seq_q, head = q.shape
    seq_k = k.shape[1]
    grid = (bn, seq_q // block_q)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, block_k=block_k, causal=causal,
                          scale=scale),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, head), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, seq_k, head), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, seq_k, head), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, head), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bn, seq_q, head), q.dtype),
            jax.ShapeDtypeStruct((bn, seq_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse


# --------------------------------------------------------------- backward --

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, block_k, causal, scale):
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    block_q = q.shape[0]
    seq_k = k_ref.shape[1]
    qi = pl.program_id(1)
    lse = lse_ref[0]                                           # [Bq, 1]
    delta = delta_ref[0]

    def body(j, dq_acc):
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse)                                   # [Bq, Bk]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq_acc + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        num_active = ((qi + 1) * block_q + block_k - 1) // block_k
    else:
        num_active = seq_k // block_k
    dq = jax.lax.fori_loop(0, num_active, body,
                           jnp.zeros_like(q, dtype=jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, block_q, causal, scale):
    k = k_ref[0].astype(jnp.float32)                           # [Bk, H]
    v = v_ref[0].astype(jnp.float32)
    block_k = k.shape[0]
    seq_q = q_ref.shape[1]
    ki = pl.program_id(1)
    num_qb = seq_q // block_q

    def body(i, carry):
        dk_acc, dv_acc = carry
        q = q_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(i * block_q, block_q), :]
        delta = delta_ref[0, pl.ds(i * block_q, block_q), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dv_new = dv_acc + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_new = dk_acc + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_new, dv_new

    zeros = jnp.zeros_like(k, dtype=jnp.float32)
    if causal:
        # q blocks before this k block's diagonal contribute nothing
        start = (ki * block_k) // block_q
        dk, dv = jax.lax.fori_loop(start, num_qb, body, (zeros, zeros))
    else:
        dk, dv = jax.lax.fori_loop(0, num_qb, body, (zeros, zeros))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd(q, k, v, out, lse, do, causal, scale, block_q, block_k,
               interpret):
    bn, seq_q, head = q.shape
    seq_k = k.shape[1]
    # delta = rowsum(dO * O) — cheap elementwise, leave to XLA fusion
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, block_k=block_k, causal=causal,
                          scale=scale),
        grid=(bn, seq_q // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, head), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, seq_k, head), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, seq_k, head), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, head), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, head), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, block_q=block_q, causal=causal,
                          scale=scale),
        grid=(bn, seq_k // block_k),
        in_specs=[
            pl.BlockSpec((1, seq_q, head), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, block_k, head), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, head), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, seq_q, head), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, seq_q, 1), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, seq_q, 1), lambda b, j: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, head), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, head), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ------------------------------------------------------------- public API --

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_attention_bnsh(q, k, v, causal, scale, interpret):
    out, _ = _fwd_rule(q, k, v, causal, scale, interpret)
    return out


def _block_candidates(seq_q, seq_k):
    """(block_q, block_k) candidates, author heuristic first."""
    qs = [b for b in (128, 256, 512, 64) if seq_q % b == 0]
    ks = [b for b in (128, 256, 512, 64) if seq_k % b == 0]
    if not qs:
        qs = [_pick_block(seq_q, DEFAULT_BLOCK_Q)]
    if not ks:
        ks = [_pick_block(seq_k, DEFAULT_BLOCK_K)]
    head = [(qs[0], ks[0])]
    rest = [(bq, bk) for bq in qs for bk in ks if (bq, bk) != head[0]]
    return head + rest


def _vmem_validate(seq_q, seq_k, head, dtype, profile="tpu-v4"):
    """Candidate screen for autotune.pick: reject (block_q, block_k) whose
    per-grid-step residency (kernel_lint's K002 model — double-buffered
    blocks) cannot fit VMEM for the forward, dq, or dkv kernel."""
    from ...framework.kernel_lint import vmem_fits

    f32 = jnp.float32

    def validate(cand):
        bq, bk = cand
        fwd = [((1, bq, head), dtype), ((1, seq_k, head), dtype),
               ((1, seq_k, head), dtype), ((1, bq, head), dtype),
               ((1, bq, 1), f32)]
        dq = [((1, bq, head), dtype), ((1, seq_k, head), dtype),
              ((1, seq_k, head), dtype), ((1, bq, head), dtype),
              ((1, bq, 1), f32), ((1, bq, 1), f32), ((1, bq, head), dtype)]
        dkv = [((1, seq_q, head), dtype), ((1, bk, head), dtype),
               ((1, bk, head), dtype), ((1, seq_q, head), dtype),
               ((1, seq_q, 1), f32), ((1, seq_q, 1), f32),
               ((1, bk, head), dtype), ((1, bk, head), dtype)]
        return all(vmem_fits(blocks, profile=profile)
                   for blocks in (fwd, dq, dkv))

    return validate


def _tuned_blocks(q, k, causal, scale, interpret):
    """Autotuned (block_q, block_k) for this shape (FLAGS_use_autotune);
    the heuristic (128-preferred divisor) wins with the flag off."""
    from . import autotune

    bn, seq_q, head = q.shape
    seq_k = k.shape[1]
    cands = _block_candidates(seq_q, seq_k)

    def measure(cand):
        bq, bk = cand
        import numpy as _np

        rng = _np.random.RandomState(0)
        shape_q = (min(bn, 8), seq_q, head)
        shape_k = (min(bn, 8), seq_k, head)
        qq = jnp.asarray(rng.rand(*shape_q), q.dtype)
        kk = jnp.asarray(rng.rand(*shape_k), q.dtype)
        vv = jnp.asarray(rng.rand(*shape_k), q.dtype)
        out, lse = _flash_fwd(qq, kk, vv, causal, scale, bq, bk, interpret)
        # measure (and VMEM-validate) the backward too: a candidate that
        # fits the fwd can overflow the bwd's working set, and training
        # pays both
        grads = _flash_bwd(qq, kk, vv, out, lse, out, causal, scale,
                           bq, bk, interpret)
        jax.block_until_ready((out, grads))  # noqa: H001 (autotune timing sync — measurement, not a serving path)

    return autotune.pick(
        "flash_attention",
        (seq_q, seq_k, head, str(q.dtype), causal),
        cands, measure=measure,
        validate=_vmem_validate(seq_q, seq_k, head, q.dtype))


def _fwd_rule(q, k, v, causal, scale, interpret):
    block_q, block_k = _tuned_blocks(q, k, causal, scale, interpret)
    out, lse = _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _bwd_rule(causal, scale, interpret, res, do):
    q, k, v, out, lse = res
    block_q, block_k = _tuned_blocks(q, k, causal, scale, interpret)
    return _flash_bwd(q, k, v, out, lse, do, causal, scale, block_q, block_k,
                      interpret)


_flash_attention_bnsh.defvjp(_fwd_rule, _bwd_rule)


def _engine_cases(engine):
    """Sweep flash at the engine's full-context envelope with per-shard
    head counts; the vjp case traces jax.grad through the custom_vjp so
    the lint sees the backward kernels (_bwd_dq/_bwd_dkv) too."""
    n = max(engine.num_heads // engine.tp, 1)
    h = engine.head_dim
    seq = engine.max_model_len
    if not supports(seq, seq, h):
        return
    sds = jax.ShapeDtypeStruct
    x = sds((engine.max_batch, seq, n, h), engine.dtype)

    def fwd(q, k, v):
        return flash_attention_pallas(q, k, v, is_causal=True)

    def vjp(q, k, v):
        def loss(*a):
            return jnp.sum(fwd(*a).astype(jnp.float32))
        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    yield registry.KernelCase(f"fwd[s{seq}]", fwd, (x, x, x), None)
    yield registry.KernelCase(f"vjp[s{seq}]", vjp, (x, x, x), None)


@registry.register_kernel(
    "flash_attention",
    fallback="paddle_tpu.ops.pallas:_xla_attention",
    parity="tests/test_pallas_kernels.py::test_flash_attention_grads",
    engine_shapes=_engine_cases,
    supports=supports,
    grad=True)
def flash_attention_pallas(q, k, v, is_causal=False, scale=None,
                           interpret=False):
    """q, k, v: [batch, seq, num_heads, head_dim] (paddle flash-attn layout).

    Returns [batch, seq, num_heads, head_dim]; differentiable.
    """
    b, sq, n, h = q.shape
    sk = k.shape[1]
    if scale is None:
        scale = 1.0 / (h ** 0.5)
    qt = q.transpose(0, 2, 1, 3).reshape(b * n, sq, h)
    kt = k.transpose(0, 2, 1, 3).reshape(b * n, sk, h)
    vt = v.transpose(0, 2, 1, 3).reshape(b * n, sk, h)
    out = _flash_attention_bnsh(qt, kt, vt, bool(is_causal), float(scale),
                                interpret)
    return out.reshape(b, n, sq, h).transpose(0, 2, 1, 3)
