"""Pallas ragged paged attention — ONE kernel for every serving phase.

Ragged Paged Attention (arxiv 2604.15464) folds chunked prefill, plain
decode, and speculative verify into a single launch over a flat ragged
batch: the step's query tokens are packed back-to-back along one token
axis, and each batch row is described by a ``(query_start, query_len,
context_len)`` descriptor instead of by its own executable.  A decode
row is simply a one-token chunk; a verify row is a K+1-token chunk; a
prefill chunk is a C-token chunk — the causal rule is identical for all
of them, because the token at absolute position ``p`` sees exactly the
``p + 1`` pool positions ``0..p``.  Shapes:

    q             [T, Nq, D]      T packed query tokens (GQA: G =
                                  Nq//Nkv query heads per KV head)
    k_pages       [NB, bs, Nkv, D] the whole paged pool, NB pages of
    v_pages       [NB, bs, Nkv, D] bs tokens each
    block_tables  [R, P] int32    page id of row r's p-th page
    row_start     [R]    int32    first flat token of row r
    row_qlen      [R]    int32    query tokens of row r (0: dead row)
    row_pos0      [R]    int32    absolute position of row r's first
                                  query token

Host contract (the engine packs exactly this): ``row_start`` is
non-decreasing, ``row_start[r] + row_qlen[r] <= T``, and a dead row
(``row_qlen == 0``) owns no tokens.  Token ``i`` of row ``r`` sits at
absolute position ``row_pos0[r] + i`` and attends over pool positions
``0 .. row_pos0[r] + i`` through row r's block table.  Tokens outside
every row (padding) come back as EXACT ZEROS.

Kernel layout: grid (Nkv, R, P), block tables and row descriptors as
scalar-prefetch operands so the BlockSpec index map dereferences
``block_tables[r, p]`` — each (kv head, row) pair walks only the pages
that row owns, with the online-softmax state held in VMEM scratch over
the padded flat token axis.  The page axis is innermost, so scratch
carries across a row's pages; the row axis is next, so a later row's
init pass reclaims whatever an earlier row's tail chunk spilled past
its own tokens (the flat axis is padded by one chunk of slack for
that spill); the output block is indexed by the kv head only and is
zeroed once per head, which is what makes dead tokens exact zeros.
Unlike the retired per-phase kernels, NOTHING is replicated on the
host: speculative verify used to materialize
``jnp.repeat(block_tables, K+1, axis=0)`` — here every row's K+1
tokens share one descriptor and one block-table row.

Like the other kernels, the 1/sqrt(D) scale is applied INSIDE; the
masked-XLA fallback (inference/llm/paged_attention.py) computes
bitwise-defined identical semantics everywhere the kernel is gated
off, and is what the engine-vs-dense token-exactness tests pin.

Under tensor parallelism the pool is sharded along the Nkv axis and
the kernel runs inside ``jax.shard_map`` with PER-SHARD head counts
and the full local pool; the scalar-prefetched descriptors (which
GSPMD could not partition through the index map) arrive replicated.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import registry

_NEG_INF = -1e30
# query tokens processed per inner chunk: one f32 sublane tile when
# G == 1, a multiple of it otherwise — the flat axis is padded by one
# chunk so a row's tail chunk can spill without leaving the block
_TQ = 8


def supports(block_size, head_dim, num_q_heads, num_kv_heads,
             total_tokens):
    """Shape gate: lane-sized head_dim, sublane-tiled pages, whole GQA
    groups, and a flat token axis the _TQ chunk walk divides."""
    return (head_dim <= 128 and block_size % 8 == 0
            and num_q_heads % num_kv_heads == 0
            and total_tokens % _TQ == 0 and total_tokens > 0)


def _ragged_kernel(bt_ref, start_ref, qlen_ref, pos0_ref,
                   *refs, block_size, group, nc, quant=False):
    """One (kv_head, row, page) program.

    Row r's tokens live at flat rows [start*G, (start+qlen)*G) of the
    padded [TG, D] query/output blocks; the chunk walk visits them
    ``_TQ`` tokens at a time with a dynamic trip count (dead rows cost
    zero chunks, a decode row exactly one).  A tail chunk may spill
    into the next row's region: spilled scratch is re-initialized by
    that row's own p == 0 pass before it is read, and spilled output
    is never written at all (the finalize store blends against the
    token-validity mask), so the zero-filled padding region stays
    exactly zero.

    ``quant=True`` (static) adds two page-scale operands after the K/V
    blocks — int8 pages dequantize AT THE OPERAND LOAD into the same
    f32 accumulation the full-precision path runs, one multiply per
    loaded slot row; no dequantized copy of the pool ever exists.
    """
    if quant:
        (q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
         o_scr, m_scr, l_scr) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, o_scr, m_scr, l_scr = refs
    r = pl.program_id(1)
    p = pl.program_id(2)
    num_pages = pl.num_programs(2)
    d = q_ref.shape[2]
    tqg = _TQ * group
    start = start_ref[r]
    qlen = qlen_ref[r]
    pos0 = pos0_ref[r]

    @pl.when((r == 0) & (p == 0))
    def _zero_output():
        # the one full-block store: every token the finalize blend
        # skips — padding, dead rows, spill — reads back exact zeros
        o_ref[...] = jnp.zeros(o_ref.shape, o_ref.dtype)

    def each_chunk(body):
        """Run ``body(c)`` for every chunk holding live tokens of this
        row — trip count is data-dependent, structure is static."""
        def step(c, carry):
            @pl.when(c * _TQ < qlen)
            def _():
                body(c)
            return carry
        jax.lax.fori_loop(0, nc, step, 0)

    @pl.when(p == 0)
    def _init():
        def init_chunk(c):
            off = (start + c * _TQ) * group
            o_scr[pl.ds(off, tqg), :] = jnp.zeros((tqg, d), jnp.float32)
            m_scr[pl.ds(off, tqg), :] = jnp.full((tqg, 1), _NEG_INF,
                                                 jnp.float32)
            l_scr[pl.ds(off, tqg), :] = jnp.zeros((tqg, 1), jnp.float32)
        each_chunk(init_chunk)

    base = p * block_size

    # pages at or past the row's deepest context hold nothing any of
    # its tokens may see; page 0 is always visible (every live token's
    # causal window contains position 0), so valid tokens accumulate
    # real state before any fully-masked page can touch them
    @pl.when(base < pos0 + qlen)
    def _accumulate():
        k = k_ref[0, :, 0, :].astype(jnp.float32)           # [bs, D]
        v = v_ref[0, :, 0, :].astype(jnp.float32)           # [bs, D]
        if quant:
            # per-(slot, head) dequant scales of this page/head block
            k = k * ks_ref[0, 0, :][:, None]
            v = v * vs_ref[0, 0, :][:, None]

        def acc_chunk(c):
            off = (start + c * _TQ) * group
            q = q_ref[0, pl.ds(off, tqg), :].astype(jnp.float32)
            s = q @ k.T / jnp.sqrt(jnp.asarray(d, jnp.float32))
            # flat row i of the chunk is query token c*_TQ + i//G of
            # this batch row, at absolute position pos0 + that index
            ti = c * _TQ + jax.lax.broadcasted_iota(
                jnp.int32, (tqg, block_size), 0) // group
            kpos = base + jax.lax.broadcasted_iota(
                jnp.int32, (tqg, block_size), 1)
            s = jnp.where((kpos <= pos0 + ti) & (ti < qlen), s,
                          _NEG_INF)
            m_prev = m_scr[pl.ds(off, tqg), :]
            l_prev = l_scr[pl.ds(off, tqg), :]
            o_prev = o_scr[pl.ds(off, tqg), :]
            m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
            pe = jnp.exp(s - m_new)
            alpha = jnp.exp(m_prev - m_new)
            o_scr[pl.ds(off, tqg), :] = o_prev * alpha + pe @ v
            m_scr[pl.ds(off, tqg), :] = m_new
            l_scr[pl.ds(off, tqg), :] = \
                l_prev * alpha + pe.sum(axis=1, keepdims=True)
        each_chunk(acc_chunk)

    @pl.when(p == num_pages - 1)
    def _finalize():
        def fin_chunk(c):
            off = (start + c * _TQ) * group
            ti = c * _TQ + jax.lax.broadcasted_iota(
                jnp.int32, (tqg, 1), 0) // group
            o = o_scr[pl.ds(off, tqg), :] \
                / jnp.maximum(l_scr[pl.ds(off, tqg), :], 1e-30)
            cur = o_ref[0, pl.ds(off, tqg), :]
            o_ref[0, pl.ds(off, tqg), :] = \
                jnp.where(ti < qlen, o.astype(o_ref.dtype), cur)
        each_chunk(fin_chunk)


def _engine_cases(engine):
    """Every launch the serving engine makes IS this kernel now: one
    case per token bucket of the collapsed ``_bucket_grid()`` family,
    with the fixed [max_batch, max_pages] descriptor rails.  The
    scalar_bounds let K003 prove the block-table prefetch indirection
    in-bounds (page ids in [0, num_blocks - 1]) and bound the row
    descriptors by the token bucket / model horizon."""
    nkv = max(engine.num_heads // engine.tp, 1)
    d = engine.head_dim
    sds = jax.ShapeDtypeStruct
    kp = sds((engine.num_blocks, engine.block_size, nkv, d),
             engine.dtype)
    rmax = engine.max_batch
    for kind, tb in engine._bucket_grid():
        if kind != "ragged":
            continue
        if not supports(engine.block_size, d, nkv, nkv, tb):
            continue
        bounds = {0: (0, engine.num_blocks - 1), 1: (0, tb),
                  2: (0, tb), 3: (0, engine.max_model_len - 1)}
        yield registry.KernelCase(
            f"ragged[{tb}]", paged_ragged_attention_pallas,
            (sds((tb, nkv, d), engine.dtype), kp, kp,
             sds((rmax, engine.max_pages), jnp.int32),
             sds((rmax,), jnp.int32), sds((rmax,), jnp.int32),
             sds((rmax,), jnp.int32)), bounds)


@registry.register_kernel(
    "paged_ragged_attention",
    fallback="paddle_tpu.inference.llm.paged_attention:"
             "paged_ragged_attention_xla",
    parity="tests/test_pallas_kernels.py::TestRaggedAttention::"
           "test_mixed_batch_parity",
    engine_shapes=_engine_cases,
    supports=supports)
def paged_ragged_attention_pallas(q, k_pages, v_pages, block_tables,
                                  row_start, row_qlen, row_pos0,
                                  interpret=False):
    """Ragged paged attention over T packed query tokens.

    Returns [T, Nq, D]; tokens outside every row are exact zeros.  See
    the module docstring for the row-descriptor layout and the host
    packing contract.
    """
    t, nq, d = q.shape
    _, bs, nkv, _ = k_pages.shape
    r, num_pages = block_tables.shape
    g = nq // nkv
    nc = t // _TQ
    tg = (t + _TQ) * g          # one chunk of spill slack
    # [T, Nkv, G, D] -> [Nkv, T*G, D]: flat row i of head j is query
    # token i // G, padded so a tail chunk never leaves the block
    qg = q.reshape(t, nkv, g, d).transpose(1, 0, 2, 3)
    qg = jnp.pad(qg.reshape(nkv, t * g, d), ((0, 0), (0, _TQ * g),
                                             (0, 0)))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(nkv, r, num_pages),
        in_specs=[
            pl.BlockSpec((1, tg, d),
                         lambda j, rr, p, bt, st, ql, p0: (j, 0, 0)),
            pl.BlockSpec((1, bs, 1, d),
                         lambda j, rr, p, bt, st, ql, p0:
                         (bt[rr, p], 0, j, 0)),
            pl.BlockSpec((1, bs, 1, d),
                         lambda j, rr, p, bt, st, ql, p0:
                         (bt[rr, p], 0, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, tg, d),
                               lambda j, rr, p, bt, st, ql, p0:
                               (j, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((tg, d), jnp.float32),
            pltpu.VMEM((tg, 1), jnp.float32),
            pltpu.VMEM((tg, 1), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_ragged_kernel, block_size=bs, group=g,
                          nc=nc),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((nkv, tg, d), q.dtype),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), row_start.astype(jnp.int32),
      row_qlen.astype(jnp.int32), row_pos0.astype(jnp.int32),
      qg, k_pages, v_pages)
    return out[:, :t * g].reshape(nkv, t, g, d).transpose(
        1, 0, 2, 3).reshape(t, nq, d)


def _quant_engine_cases(engine):
    """Launch shapes of the int8-KV ragged family — yielded only for a
    KV-quantized engine (a full-precision engine never launches this
    kernel, so its sweep stays the bf16 entry's).  Same descriptor
    rails and scalar bounds as ``_engine_cases``; the pools are int8
    and each carries its [NB, Nkv, bs] f32 page-scale operand."""
    if not getattr(engine, "_kv_quant", False):
        return
    nkv = max(engine.num_heads // engine.tp, 1)
    d = engine.head_dim
    sds = jax.ShapeDtypeStruct
    kp = sds((engine.num_blocks, engine.block_size, nkv, d), jnp.int8)
    sp = sds((engine.num_blocks, nkv, engine.block_size), jnp.float32)
    rmax = engine.max_batch
    for kind, tb in engine._bucket_grid():
        if kind != "ragged":
            continue
        if not supports(engine.block_size, d, nkv, nkv, tb):
            continue
        bounds = {0: (0, engine.num_blocks - 1), 1: (0, tb),
                  2: (0, tb), 3: (0, engine.max_model_len - 1)}
        yield registry.KernelCase(
            f"ragged_quant[{tb}]", paged_ragged_attention_quant_pallas,
            (sds((tb, nkv, d), engine.dtype), kp, kp, sp, sp,
             sds((rmax, engine.max_pages), jnp.int32),
             sds((rmax,), jnp.int32), sds((rmax,), jnp.int32),
             sds((rmax,), jnp.int32)), bounds)


@registry.register_kernel(
    "paged_ragged_attention_quant",
    fallback="paddle_tpu.inference.llm.paged_attention:"
             "paged_ragged_attention_quant_xla",
    parity="tests/test_pallas_kernels.py::TestRaggedAttentionQuant::"
           "test_mixed_batch_parity",
    engine_shapes=_quant_engine_cases,
    supports=supports)
def paged_ragged_attention_quant_pallas(q, k_pages, v_pages, k_scales,
                                        v_scales, block_tables,
                                        row_start, row_qlen, row_pos0,
                                        interpret=False):
    """Ragged paged attention over an INT8 pool with in-kernel dequant.

    Same contract as :func:`paged_ragged_attention_pallas`, plus
    ``k_scales``/``v_scales`` [NB, Nkv, bs] float32 — one symmetric
    dequant scale per (page, kv head, slot), written by the engine's
    quantized append (inference/llm/quant.py).  Each (kv head, row,
    page) program loads its int8 [bs, D] page block and its [bs] scale
    row, dequantizes in f32 registers, and runs the identical
    online-softmax walk — HBM reads stay 1 byte per pool element."""
    t, nq, d = q.shape
    _, bs, nkv, _ = k_pages.shape
    r, num_pages = block_tables.shape
    g = nq // nkv
    nc = t // _TQ
    tg = (t + _TQ) * g          # one chunk of spill slack
    qg = q.reshape(t, nkv, g, d).transpose(1, 0, 2, 3)
    qg = jnp.pad(qg.reshape(nkv, t * g, d), ((0, 0), (0, _TQ * g),
                                             (0, 0)))

    page_spec = pl.BlockSpec((1, bs, 1, d),
                             lambda j, rr, p, bt, st, ql, p0:
                             (bt[rr, p], 0, j, 0))
    scale_spec = pl.BlockSpec((1, 1, bs),
                              lambda j, rr, p, bt, st, ql, p0:
                              (bt[rr, p], j, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(nkv, r, num_pages),
        in_specs=[
            pl.BlockSpec((1, tg, d),
                         lambda j, rr, p, bt, st, ql, p0: (j, 0, 0)),
            page_spec,
            page_spec,
            scale_spec,
            scale_spec,
        ],
        out_specs=pl.BlockSpec((1, tg, d),
                               lambda j, rr, p, bt, st, ql, p0:
                               (j, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((tg, d), jnp.float32),
            pltpu.VMEM((tg, 1), jnp.float32),
            pltpu.VMEM((tg, 1), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_ragged_kernel, block_size=bs, group=g,
                          nc=nc, quant=True),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((nkv, tg, d), q.dtype),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), row_start.astype(jnp.int32),
      row_qlen.astype(jnp.int32), row_pos0.astype(jnp.int32),
      qg, k_pages, v_pages, k_scales, v_scales)
    return out[:, :t * g].reshape(nkv, t, g, d).transpose(
        1, 0, 2, 3).reshape(t, nq, d)
