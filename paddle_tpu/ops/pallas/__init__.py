"""Pallas TPU kernels for the hot ops.

Analog of the reference's hand-fused CUDA kernels
(paddle/phi/kernels/fusion/, flash_attn at
paddle/phi/kernels/gpu/flash_attn_kernel.cu).  Selection order:
Pallas kernel (TPU, flag-gated) → XLA composition fallback (works everywhere,
still fuses well).  ``FLAGS_use_pallas_kernels`` toggles.
"""

import jax
import jax.numpy as jnp

from ...framework.flags import get_flags


def _use_pallas():
    return (jax.default_backend() == "tpu"
            and get_flags("FLAGS_use_pallas_kernels")["FLAGS_use_pallas_kernels"])


def _xla_attention(q, k, v, attn_mask=None, is_causal=False, dropout_p=0.0,
                   dropout_key=None, scale=None):
    """Reference XLA attention on [B, T, N, H] (paddle flash-attn layout).

    Matmuls stay in the input dtype (bf16 on the MXU) with f32 accumulation
    via ``preferred_element_type``; only the softmax runs in f32.  Upcasting
    the operands themselves would push the score/context matmuls onto the
    4x-slower f32 MXU path — measured as the dominant per-step cost on v5e.
    """
    if scale is None:
        scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], dtype=jnp.float32))
    logits = jnp.einsum("btnh,bsnh->bnts", q, k,
                        preferred_element_type=jnp.float32) * scale
    if is_causal:
        t, s = logits.shape[-2], logits.shape[-1]
        causal = jnp.tril(jnp.ones((t, s), dtype=bool), k=s - t)
        logits = jnp.where(causal, logits, jnp.finfo(jnp.float32).min)
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            logits = jnp.where(attn_mask, logits, jnp.finfo(jnp.float32).min)
        else:
            logits = logits + attn_mask.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    if dropout_p > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    out = jnp.einsum("bnts,bsnh->btnh", probs.astype(q.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def flash_attention(q, k, v, attn_mask=None, is_causal=False, dropout_p=0.0,
                    dropout_key=None, scale=None):
    """Flash attention on [batch, seq, num_heads, head_dim].

    When ``dropout_p > 0`` and no explicit key is given, a key is drawn from
    the global RNG (paddle.seed-controlled) — attention dropout must not be
    silently dropped.  Attention dropout forces the XLA path (the Pallas
    kernel is dropout-free, like most production flash kernels at
    inference/bf16 pretrain settings)."""
    if dropout_p > 0.0 and dropout_key is None:
        from ...framework.random import get_rng_key
        dropout_key = get_rng_key()
    if (_use_pallas() and attn_mask is None and dropout_p == 0.0
            and scale is None):
        from .attention_kernel import flash_attention_pallas, supports
        # causal masking in the kernel is top-left aligned; for seq_q !=
        # seq_k the paddle/XLA semantics are bottom-right aligned, so only
        # self-attention-shaped causal inputs take the kernel path
        causal_ok = (not is_causal) or q.shape[1] == k.shape[1]
        # Below this sequence length the fused XLA attention is faster on
        # TPU (profiled on v5e: the kernel's small per-program blocks and
        # lane-padded head_dim lose to the MXU-saturating einsum); flash
        # pays off once the [T, S] score matrix dominates HBM.
        min_seq = get_flags("FLAGS_flash_min_seqlen")["FLAGS_flash_min_seqlen"]
        if (causal_ok and q.shape[1] >= int(min_seq)
                and supports(q.shape[1], k.shape[1], q.shape[3])):
            return flash_attention_pallas(q, k, v, is_causal)
    return _xla_attention(q, k, v, attn_mask=attn_mask, is_causal=is_causal,
                          dropout_p=dropout_p, dropout_key=dropout_key,
                          scale=scale)


def pick_block(size, preferred, candidates=(512, 256, 128, 64, 32, 16, 8)):
    """Largest candidate <= preferred that divides ``size`` (shared block
    -size heuristic for the Pallas kernels)."""
    for b in (preferred,) + tuple(candidates):
        if b <= preferred and size % b == 0:
            return b
    return None
