"""Kernel registry — the contract surface for the K005 lint rule.

Every Pallas kernel entry point in this package registers itself with
:func:`register_kernel`, declaring the three things a kernel must never
ship without:

- **fallback** — a lazily-resolved ``"module.path:attr"`` string naming
  the XLA composition with identical semantics (lazy so registration
  never imports the serving stack and cannot create import cycles);
- **parity** — a pytest node id (``tests/file.py::Class::test``) for the
  interpret-mode parity test that pins kernel-vs-fallback numerics on
  CPU, where the dev loop actually runs;
- **engine_shapes** — a builder mapping a live ``LLMEngine`` to the
  concrete ``(label, traceable_fn, abstract_args, scalar_bounds)``
  cases the kernel is launched with across the engine's bucket grid, so
  ``graph-lint kernels`` sweeps the registry over the shapes serving
  really compiles, not a synthetic corpus.  ``scalar_bounds`` maps
  scalar-prefetch operand positions to inclusive ``(lo, hi)`` value
  ranges (e.g. block-table entries are page ids in
  ``[0, num_blocks - 1]``), which is what lets K003 prove index maps
  in-bounds through the prefetch indirection.

The decorator is a zero-overhead passthrough: it records the entry and
returns the function unchanged, so registration costs nothing on the
serving hot path.  :mod:`paddle_tpu.framework.kernel_lint` consumes the
registry; nothing here imports jax.
"""

import importlib
from collections import namedtuple

__all__ = [
    "KernelCase", "KernelEntry", "register_kernel", "kernel_registry",
    "load_all", "resolve_fallback", "KERNEL_MODULES",
]

# One lint/sweep case: ``fn(*args)`` must be traceable by jax.make_jaxpr
# (args are ShapeDtypeStructs) and reach the kernel's pallas_call —
# entries whose backward matters wrap fn in jax.grad so the sweep sees
# the bwd kernels too.
KernelCase = namedtuple("KernelCase",
                        ["label", "fn", "args", "scalar_bounds"])

# Modules that define kernels; ``load_all`` imports exactly these so a
# registry consumer sees every entry without importing the whole tree.
KERNEL_MODULES = (
    "attention_kernel",
    "decode_attention_kernel",
    "ragged_attention_kernel",
    "layernorm_kernel",
)

_REGISTRY = {}


class KernelEntry:
    """One registered kernel entry point (see module docstring)."""

    __slots__ = ("name", "fn", "fallback", "parity", "engine_shapes",
                 "supports", "grad")

    def __init__(self, name, fn, fallback, parity, engine_shapes,
                 supports, grad):
        self.name = name
        self.fn = fn
        self.fallback = fallback
        self.parity = parity
        self.engine_shapes = engine_shapes
        self.supports = supports
        self.grad = grad

    def __repr__(self):
        return f"KernelEntry({self.name!r} -> {self.fallback!r})"


def register_kernel(name, *, fallback, parity, engine_shapes,
                    supports=None, grad=False):
    """Decorator registering a kernel entry point under ``name``.

    ``supports`` is the module's hand-written shape gate (consulted by
    the supports-vs-lint consistency tests); ``grad=True`` declares that
    the entry differentiates through a custom_vjp and its
    ``engine_shapes`` cases include a grad-traced case covering the
    backward kernels.
    """
    def deco(fn):
        _REGISTRY[name] = KernelEntry(name, fn, fallback, parity,
                                      engine_shapes, supports, grad)
        return fn
    return deco


def unregister(name):
    """Remove an entry (test hook for seeded-contract-violation specs)."""
    return _REGISTRY.pop(name, None)


def load_all():
    """Import every kernel module, then return the full registry."""
    for mod in KERNEL_MODULES:
        importlib.import_module(f"{__package__}.{mod}")
    return dict(_REGISTRY)


def kernel_registry():
    return load_all()


def resolve_fallback(entry):
    """Resolve an entry's ``"module.path:attr"`` fallback to a callable.

    Raises (ImportError/AttributeError/ValueError) when the contract is
    broken — K005 converts that into a finding.
    """
    spec = entry.fallback if isinstance(entry, KernelEntry) else entry
    if not spec or ":" not in spec:
        raise ValueError(f"fallback spec {spec!r} is not 'module:attr'")
    mod_name, _, attr = spec.partition(":")
    fn = getattr(importlib.import_module(mod_name), attr)
    if not callable(fn):
        raise ValueError(f"fallback {spec!r} resolved to a non-callable")
    return fn
