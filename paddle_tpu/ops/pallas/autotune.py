"""Pallas kernel autotune — block-size selection cache.

Reference parity: the kernel/layout autotune cache at
paddle/phi/kernels/autotune/ (cache.h, auto_tune_base.h): measure candidate
configs once per (kernel, shape key), remember the winner.  On TPU, XLA
autotunes its own fusions; what remains worth tuning is OUR Pallas grid
/block choices, where VMEM footprint vs. occupancy is shape-dependent.

Off by default (``FLAGS_use_autotune``): the first sighting of a shape
otherwise pays ``len(candidates)`` compiles.  With the flag off the first
candidate (the kernel author's heuristic) wins unconditionally.  Results
persist in-process and, when ``PADDLE_TPU_AUTOTUNE_CACHE`` names a file,
across processes as JSON.
"""

import json
import os
import tempfile
import threading
import time

_CACHE = {}
_LOCK = threading.Lock()
_loaded_file = False


def _cache_file():
    return os.environ.get("PADDLE_TPU_AUTOTUNE_CACHE")


def _load_file_once():
    global _loaded_file
    path = _cache_file()
    if _loaded_file or not path or not os.path.exists(path):
        _loaded_file = True
        return
    try:
        with open(path) as f:
            for k, v in json.load(f).items():
                winner, tuned = v
                if isinstance(winner, list):
                    winner = tuple(winner)
                _CACHE.setdefault(k, (winner, bool(tuned)))
    except Exception:
        pass
    _loaded_file = True


def _save_file():
    # Atomic: concurrent processes sharing PADDLE_TPU_AUTOTUNE_CACHE must
    # never observe a torn/partial JSON (truncate-then-write loses the whole
    # cache if a reader races the writer or the writer dies mid-dump).
    path = _cache_file()
    if not path:
        return
    tmp = None
    try:
        fd, tmp = tempfile.mkstemp(
            dir=os.path.dirname(os.path.abspath(path)) or ".",
            prefix=os.path.basename(path) + ".")
        with os.fdopen(fd, "w") as f:
            json.dump({k: v for k, v in _CACHE.items()}, f)
        os.replace(tmp, path)
        tmp = None
    except Exception:
        pass
    finally:
        if tmp is not None:
            try:
                os.unlink(tmp)
            except OSError:
                pass


def _enabled():
    from ...framework.flags import get_flags

    try:
        return bool(get_flags("FLAGS_use_autotune")["FLAGS_use_autotune"])
    except Exception:
        return False


def autotune_cache_info():
    with _LOCK:
        return dict(_CACHE)


def autotune_cache_clear():
    with _LOCK:
        _CACHE.clear()


def pick(kernel, key, candidates, measure=None, warmup=1, iters=3,
         validate=None):
    """Return the winning candidate for ``(kernel, key)``.

    ``candidates``: non-empty list, first = author heuristic (the flag-off
    winner).  ``measure(candidate) -> None`` runs the kernel once with that
    config on real inputs; it is timed with ``warmup`` untimed runs then
    best-of-``iters``.  A candidate whose measure raises is skipped (e.g.
    VMEM overflow for an oversized block).

    ``validate(candidate) -> bool`` statically screens candidates before
    any compile/measure (kernel_lint's K002 VMEM residency model is the
    intended screen) — rejected candidates never burn a compile.  If the
    screen rejects everything the original list is kept: the model is
    advisory and the measure path's try/except stays the backstop.
    """
    if not candidates:
        raise ValueError("no candidates")
    if validate is not None:
        screened = [c for c in candidates if validate(c)]
        if screened:
            candidates = screened
    ck = f"{kernel}|{key}"
    want_tuning = measure is not None and _enabled() and len(candidates) > 1
    with _LOCK:
        _load_file_once()
        if ck in _CACHE:
            winner, tuned = _CACHE[ck]
            # a heuristic (untuned) entry does not satisfy a tuning request
            # — flipping FLAGS_use_autotune on later must still measure
            if tuned or not want_tuning:
                return winner
    if not want_tuning:
        winner, tuned = candidates[0], False
    else:
        tuned = True
        best_t, winner = float("inf"), candidates[0]
        for cand in candidates:
            try:
                for _ in range(warmup):
                    measure(cand)
                t = float("inf")
                for _ in range(iters):
                    t0 = time.perf_counter()
                    measure(cand)
                    t = min(t, time.perf_counter() - t0)
            except Exception:
                continue
            if t < best_t:
                best_t, winner = t, cand
    with _LOCK:
        _CACHE[ck] = (winner, tuned)
        _save_file()
    return winner
