"""Generic pooling kernels (phi `pool2d`/`pool3d`/`max_pool*_with_index`/
`unpool`).

Reference: paddle/phi/kernels/funcs/pooling.* + pool kernels.  Built on
``lax.reduce_window`` which XLA maps directly to the TPU vector unit; the
with-index variants reduce over (value, linear-index) pairs so the argmax
comes out of one fused reduce_window.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .registry import op


def _tup(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


def _normalize_pads(paddings, nd):
    """Per-spatial-dim (lo, hi) pairs from any accepted spelling: int,
    [p_d...], [(lo, hi)...], or the reference's FLAT per-side form
    [lo0, hi0, lo1, hi1, ...] (pool2d attr [pt, pb, pl, pr])."""
    pads = _tup(paddings, nd)
    if len(pads) == 2 * nd and all(
            isinstance(p, (int, np.integer)) for p in pads):
        return tuple((int(pads[2 * i]), int(pads[2 * i + 1]))  # noqa: H001 (padding attrs)
                     for i in range(nd))
    return tuple((p, p) if isinstance(p, int) else tuple(p)
                 for p in pads)


def _window_dims(ksize, strides, paddings, nd, channel_last):
    if channel_last:
        return ((1,) + ksize + (1,), (1,) + strides + (1,),
                ((0, 0),) + paddings + ((0, 0),))
    return ((1, 1) + ksize, (1, 1) + strides, ((0, 0), (0, 0)) + paddings)


def _pool_nd(x, ksize, strides, paddings, pooling_type, exclusive,
             adaptive, ceil_mode, data_format, nd):
    channel_last = data_format in ("NHWC", "NDHWC", "NLC")
    spatial = (list(range(1, nd + 1)) if channel_last
               else list(range(2, nd + 2)))
    if adaptive:
        # adaptive: output size = ksize; use mean/max over computed bins
        out_sizes = _tup(ksize, nd)
        out = x
        for ax, osz in zip(spatial, out_sizes):
            isz = out.shape[ax]
            # bin boundaries are shape-derived (static) — numpy keeps
            # the path jit-traceable
            starts = (np.arange(osz) * isz) // osz
            ends = ((np.arange(osz) + 1) * isz + osz - 1) // osz
            segs = []
            for i in range(osz):
                s, e = int(starts[i]), int(ends[i])  # noqa: H001 (shape-derived bins)
                sl = [slice(None)] * out.ndim
                sl[ax] = slice(s, max(e, s + 1))
                seg = out[tuple(sl)]
                red = (jnp.max if pooling_type == "max" else jnp.mean)
                segs.append(red(seg, axis=ax, keepdims=True))
            out = jnp.concatenate(segs, axis=ax)
        return out
    ksize = _tup(ksize, nd)
    strides = _tup(strides, nd)
    pads = _normalize_pads(paddings, nd)
    if ceil_mode:
        new_pads = []
        for i, ax in enumerate(spatial):
            isz = x.shape[ax]
            p_lo, p_hi = pads[i]
            span = isz + p_lo + p_hi - ksize[i]
            extra = (-span) % strides[i] if span % strides[i] else 0
            new_pads.append((p_lo, p_hi + extra))
        pads = tuple(new_pads)
    wdims, wstrides, wpads = _window_dims(ksize, strides, pads, nd,
                                          channel_last)
    if pooling_type == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else \
            jnp.iinfo(x.dtype).min
        return lax.reduce_window(x, jnp.asarray(init, x.dtype), lax.max,
                                 wdims, wstrides, wpads)
    xs = lax.reduce_window(x.astype(jnp.float32), 0.0, lax.add, wdims,
                           wstrides, wpads)
    if exclusive:
        ones = jnp.ones_like(x, jnp.float32)
        cnt = lax.reduce_window(ones, 0.0, lax.add, wdims, wstrides, wpads)
        return (xs / jnp.maximum(cnt, 1.0)).astype(x.dtype)
    denom = 1.0
    for k in ksize:
        denom *= k
    return (xs / denom).astype(x.dtype)


@op()
def pool2d(x, kernel_size, strides=1, paddings=0, ceil_mode=False,
           exclusive=True, data_format="NCHW", pooling_type="max",
           global_pooling=False, adaptive=False, padding_algorithm="EXPLICIT"):
    if global_pooling:
        spatial = (1, 2) if data_format == "NHWC" else (2, 3)
        red = jnp.max if pooling_type == "max" else jnp.mean
        return red(x, axis=spatial, keepdims=True)
    return _pool_nd(x, kernel_size, strides, paddings, pooling_type,
                    exclusive, adaptive, ceil_mode, data_format, 2)


@op()
def pool3d(x, kernel_size, strides=1, paddings=0, ceil_mode=False,
           exclusive=True, data_format="NCDHW", pooling_type="max",
           global_pooling=False, adaptive=False, padding_algorithm="EXPLICIT"):
    if global_pooling:
        spatial = (1, 2, 3) if data_format == "NDHWC" else (2, 3, 4)
        red = jnp.max if pooling_type == "max" else jnp.mean
        return red(x, axis=spatial, keepdims=True)
    return _pool_nd(x, kernel_size, strides, paddings, pooling_type,
                    exclusive, adaptive, ceil_mode, data_format, 3)


def _max_pool_with_index(x, ksize, strides, paddings, nd, adaptive):
    """Reduce over (value, flat-spatial-index) pairs in one reduce_window."""
    spatial_shape = x.shape[2:]
    flat = 1
    for s in spatial_shape:
        flat *= s
    idx = jnp.arange(flat).reshape(spatial_shape)
    idx = jnp.broadcast_to(idx, x.shape)
    if adaptive:
        return _adaptive_max_with_index(x, _tup(ksize, nd), nd)
    ksize = _tup(ksize, nd)
    strides = _tup(strides, nd)
    pads = _normalize_pads(paddings, nd)
    wdims, wstrides, wpads = _window_dims(ksize, strides, pads, nd, False)

    def reducer(a, b):
        av, ai = a
        bv, bi = b
        take_b = bv > av
        return (jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai))

    init = (jnp.asarray(-jnp.inf, jnp.float32),
            jnp.asarray(flat, jnp.int32))
    # Differentiable values come from a plain max reduce_window; the
    # paired (value, index) window runs on a stop_gradient copy — the
    # variadic reduce_window has no VJP rule for a mixed float/int pair
    # (symbolic-Zero cotangent on the index output breaks its tree),
    # caught by the round-3 grad sweep.
    vals = lax.reduce_window(x.astype(jnp.float32),
                             jnp.asarray(-jnp.inf, jnp.float32), lax.max,
                             wdims, wstrides, wpads)
    _, idxs = lax.reduce_window(
        (lax.stop_gradient(x).astype(jnp.float32), idx.astype(jnp.int32)),
        init, reducer, wdims, wstrides, wpads)
    return vals.astype(x.dtype), idxs


def _adaptive_max_with_index(x, out_sizes, nd):
    """Per-bin max + flat spatial argmax for adaptive pooling."""
    spatial_shape = x.shape[2:]
    strides = [1] * nd
    for i in range(nd - 2, -1, -1):
        strides[i] = strides[i + 1] * spatial_shape[i + 1]

    def bins(isz, osz):
        return [((i * isz) // osz, max(((i + 1) * isz + osz - 1) // osz,
                                       (i * isz) // osz + 1))
                for i in range(osz)]

    all_bins = [bins(spatial_shape[i], out_sizes[i]) for i in range(nd)]
    vals_rows, idx_rows = [], []
    import itertools
    out_spatial = tuple(out_sizes)
    vals = jnp.zeros(x.shape[:2] + out_spatial, jnp.float32)
    idxs = jnp.zeros(x.shape[:2] + out_spatial, jnp.int32)
    for pos in itertools.product(*[range(s) for s in out_spatial]):
        sl = [slice(None), slice(None)]
        offs = 0
        for d, p in enumerate(pos):
            s, e = all_bins[d][p]
            sl.append(slice(s, e))
            offs += s * strides[d]
        seg = x[tuple(sl)].astype(jnp.float32)
        segf = seg.reshape(seg.shape[:2] + (-1,))
        am = jnp.argmax(segf, axis=-1)
        # unflatten local argmax to global flat index
        loc_shape = seg.shape[2:]
        loc_strides = [1] * nd
        for i in range(nd - 2, -1, -1):
            loc_strides[i] = loc_strides[i + 1] * loc_shape[i + 1]
        gidx = jnp.zeros_like(am)
        rem = am
        for d in range(nd):
            q = rem // loc_strides[d]
            rem = rem % loc_strides[d]
            gidx = gidx + q * strides[d]
        gidx = gidx + offs
        vals = vals.at[(slice(None), slice(None)) + pos].set(
            jnp.max(segf, axis=-1))
        idxs = idxs.at[(slice(None), slice(None)) + pos].set(
            gidx.astype(jnp.int32))
    return vals.astype(x.dtype), idxs


@op()
def max_pool2d_with_index(x, kernel_size, strides=None, paddings=0,
                          global_pooling=False, adaptive=False,
                          ceil_mode=False):
    if strides is None:
        strides = kernel_size
    if global_pooling:
        kernel_size = x.shape[2:]
        strides = kernel_size
        paddings = 0
    return _max_pool_with_index(x, kernel_size, strides, paddings, 2,
                                adaptive)


@op()
def max_pool3d_with_index(x, kernel_size, strides=None, paddings=0,
                          global_pooling=False, adaptive=False,
                          ceil_mode=False):
    if strides is None:
        strides = kernel_size
    if global_pooling:
        kernel_size = x.shape[2:]
        strides = kernel_size
        paddings = 0
    return _max_pool_with_index(x, kernel_size, strides, paddings, 3,
                                adaptive)


maxpool = op("maxpool")(lambda x, kernel_size, strides=1, paddings=0:
                        _pool_nd(x, kernel_size, strides, paddings, "max",
                                 True, False, False, "NCHW", 2))


@op()
def unpool(x, indices, kernel_size=2, strides=2, paddings=0,
           output_size=None, data_format="NCHW"):
    """Max-unpooling: scatter values back to argmax positions."""
    n, c, h, w = x.shape
    if output_size is None:
        ks = _tup(kernel_size, 2)
        st = _tup(strides, 2)
        pd = _tup(paddings, 2)
        oh = (h - 1) * st[0] - 2 * pd[0] + ks[0]
        ow = (w - 1) * st[1] - 2 * pd[1] + ks[1]
    else:
        oh, ow = int(output_size[-2]), int(output_size[-1])
    out = jnp.zeros((n, c, oh * ow), x.dtype)
    flat_idx = indices.reshape(n, c, -1).astype(jnp.int32)
    vals = x.reshape(n, c, -1)
    out = jax.vmap(jax.vmap(lambda o, i, v: o.at[i].set(v)))(
        out, flat_idx, vals)
    return out.reshape(n, c, oh, ow)


@op()
def unpool3d(x, indices, kernel_size=2, strides=2, paddings=0,
             output_size=None, data_format="NCDHW"):
    n, c, d, h, w = x.shape
    if output_size is None:
        ks = _tup(kernel_size, 3)
        st = _tup(strides, 3)
        pd = _tup(paddings, 3)
        od = (d - 1) * st[0] - 2 * pd[0] + ks[0]
        oh = (h - 1) * st[1] - 2 * pd[1] + ks[1]
        ow = (w - 1) * st[2] - 2 * pd[2] + ks[2]
    else:
        od, oh, ow = (int(s) for s in output_size[-3:])
    out = jnp.zeros((n, c, od * oh * ow), x.dtype)
    flat_idx = indices.reshape(n, c, -1).astype(jnp.int32)
    vals = x.reshape(n, c, -1)
    out = jax.vmap(jax.vmap(lambda o, i, v: o.at[i].set(v)))(
        out, flat_idx, vals)
    return out.reshape(n, c, od, oh, ow)
