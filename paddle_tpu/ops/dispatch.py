"""Eager op dispatch: one generic mechanism for forward + autograd recording.

Replaces the reference's generated per-op pipeline (Python-C wrapper →
``{op}_ad_func`` → C++ API → kernel dispatch; see SURVEY §3.1 and templates at
paddle/fluid/eager/auto_code_generator/generator/eager_gen.py:210).  Here every
op is a pure jax function; ``apply_op`` substitutes Tensor arguments, runs the
function (under ``jax.vjp`` when grads are needed), wraps outputs, and records
one GradNode.  Under ``jax.jit`` tracing the same path runs with tracers in
``Tensor._data`` — the tape still records, but jit train steps use the
functional ``jax.grad`` path instead of the tape.
"""

import time

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..framework import mode
from ..framework.flags import get_flags
from ..autograd.tape import GradNode
from ..profiler import host_events_active, record_host_event

_is_tensor = lambda x: isinstance(x, Tensor)


def apply_op(name, fn, args, kwargs):
    """Run ``fn`` (pure jax) over ``args``/``kwargs`` with Tensors substituted.

    Any ``Tensor`` found anywhere in the (args, kwargs) pytree becomes a
    differentiable input; everything else is closed over as a static attribute.
    Returns Tensor-wrapped outputs mirroring the output pytree of ``fn``.
    """
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs), is_leaf=_is_tensor)
    t_pos = [i for i, l in enumerate(leaves) if isinstance(l, Tensor)]
    tensors = [leaves[i] for i in t_pos]
    datas = [t._data for t in tensors]
    from ..amp import amp_cast_inputs
    datas = amp_cast_inputs(name, datas)

    def pure(*tdatas):
        new_leaves = list(leaves)
        for i, d in zip(t_pos, tdatas):
            new_leaves[i] = d
        a, k = jax.tree_util.tree_unflatten(treedef, new_leaves)
        return fn(*a, **k)

    requires_grad = (mode.is_grad_enabled()
                     and any(not t.stop_gradient for t in tensors))

    # profiler RecordEvent parity: the reference generates a record-event
    # into every ad_func (eager_gen.py "Dygraph Record Event")
    timing = host_events_active()
    t0 = time.perf_counter() if timing else 0.0

    if requires_grad:
        out, vjp_fn = jax.vjp(pure, *datas)
    else:
        out = pure(*datas)
        vjp_fn = None

    if timing:
        record_host_event(name, t0, time.perf_counter() - t0)

    out_leaves, out_treedef = jax.tree_util.tree_flatten(out)
    node = None
    if requires_grad:
        avals = [jax.ShapeDtypeStruct(o.shape, o.dtype) for o in out_leaves]
        node = GradNode(name, vjp_fn, tensors, avals, out_treedef,
                        primal_fn=pure,
                        in_dtypes=tuple(d.dtype for d in datas))
        if get_flags("FLAGS_check_nan_inf")["FLAGS_check_nan_inf"]:
            _check_nan_inf(name, out_leaves)

    wrapped = []
    for i, o in enumerate(out_leaves):
        differentiable = requires_grad and jnp.issubdtype(o.dtype, jnp.inexact)
        t = Tensor(o, stop_gradient=not differentiable)
        if differentiable:
            t._node = node
            t._out_idx = i
        wrapped.append(t)
    return jax.tree_util.tree_unflatten(out_treedef, wrapped)


def _check_nan_inf(name, out_leaves):
    """FLAGS_check_nan_inf parity (paddle/fluid/eager/nan_inf_utils.cc)."""
    for o in out_leaves:
        if isinstance(o, jax.core.Tracer):
            return  # cannot check under trace
        if jnp.issubdtype(o.dtype, jnp.inexact) and not bool(jnp.isfinite(o).all()):
            raise FloatingPointError(f"NaN or Inf detected in output of op '{name}'")
