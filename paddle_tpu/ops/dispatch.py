"""Eager op dispatch: one generic mechanism for forward + autograd recording.

Replaces the reference's generated per-op pipeline (Python-C wrapper →
``{op}_ad_func`` → C++ API → kernel dispatch; see SURVEY §3.1 and templates at
paddle/fluid/eager/auto_code_generator/generator/eager_gen.py:210).  Here every
op is a pure jax function; ``apply_op`` substitutes Tensor arguments, runs the
function (under ``jax.vjp`` when grads are needed), wraps outputs, and records
one GradNode.  Under ``jax.jit`` tracing the same path runs with tracers in
``Tensor._data`` — the tape still records, but jit train steps use the
functional ``jax.grad`` path instead of the tape.
"""

import time
from collections import OrderedDict

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..framework import mode
from ..framework.flags import get_flags
from ..autograd.tape import GradNode
from ..profiler import host_events_active, record_host_event

_is_tensor = lambda x: isinstance(x, Tensor)


# ---------------------------------------------------------------------------
# Steady-state dispatch cache.
#
# The reference fights for ~us per-op eager dispatch (SURVEY §3.1: the whole
# generated Python-C → ad_func → C++ API pipeline exists to keep the
# per-call overhead off the kernel).  Here the analogous cost is jax
# op-by-op dispatch plus a fresh `jax.vjp` trace on EVERY eager call.  The
# cache below keys on (op, impl fn, call structure, static args, input
# shapes/dtypes, grad mode) and stores a jitted forward — for grad calls a
# jitted `jax.vjp` whose pullback (a pytree-registered `jax.api.VJP`) round
# -trips out of jit and is later executed through one shared jitted runner —
# so steady-state eager dispatch runs one cached XLA executable per op.
#
# Per-call closures (dropout and friends re-register a fresh fn capturing
# the rng key each call) never repeat a key; the LRU bound keeps them from
# growing the table.  An entry only compiles on its SECOND sighting, after
# the first (uncached) run has proven every output leaf is a jax array.
# ---------------------------------------------------------------------------

import threading as _threading

_DISPATCH_CACHE = OrderedDict()
_DISPATCH_CACHE_MAX = 2048
_DISPATCH_CACHE_LOCK = _threading.Lock()
_dispatch_cache_enabled = True

# Calls proven untraceable (value-dependent output shapes: nonzero, unique,
# masked_select...).  Banned by SHAPE-GENERALIZED key — (op, fn, structure,
# static args, grad mode) WITHOUT the input avals — otherwise every new
# shape of such a call pays one failed jit trace + exception; but a static
# -arg combo or grad mode that traces fine keeps its cache.
_UNJITTABLE_OPS = set()

# Only these prove the OP ITSELF cannot trace; anything else (device OOM,
# transient XLA errors) stays a per-key ban so one bad call can't disable
# caching for an op name process-wide.
_TRACE_ERRORS = tuple(
    e for e in (getattr(jax.errors, n, None) for n in (
        "ConcretizationTypeError", "TracerArrayConversionError",
        "TracerBoolConversionError", "TracerIntegerConversionError",
        "NonConcreteBooleanIndexError"))
    if e is not None)


class _CacheEntry:
    __slots__ = ("jittable", "compiled", "banned")

    def __init__(self):
        self.jittable = False
        self.compiled = None
        self.banned = False  # trace failed once: never compile this key


def enable_dispatch_cache(flag=True):
    """Toggle the eager jit-dispatch cache (on by default)."""
    global _dispatch_cache_enabled
    _dispatch_cache_enabled = bool(flag)


def dispatch_cache_clear():
    with _DISPATCH_CACHE_LOCK:
        _DISPATCH_CACHE.clear()
        _UNJITTABLE_OPS.clear()
    # the shared pullback runner holds one backward executable per distinct
    # forward trace; release those too
    _run_vjp.clear_cache()


def dispatch_cache_info():
    with _DISPATCH_CACHE_LOCK:
        return {"entries": len(_DISPATCH_CACHE),
                "compiled": sum(1 for e in _DISPATCH_CACHE.values()
                                if e.compiled is not None)}


def _dispatch_key(name, fn, treedef, leaves, t_pos, datas, requires_grad):
    """Build (cache key, shape-generalized ban key), or (None, None) if any
    static arg is unhashable."""
    t_set = set(t_pos)
    try:
        statics = tuple((i, type(l), l) for i, l in enumerate(leaves)
                        if i not in t_set)
        avals = tuple((d.shape, d.dtype, bool(getattr(d, "weak_type", False)))
                      for d in datas)
        key = (name, fn, treedef, statics, avals, requires_grad)
        hash(key)
    except TypeError:
        return None, None
    return key, (name, fn, treedef, statics, requires_grad)


_debug_hook = None


def set_debug_hook(fn):
    """Install/remove the amp.debugging per-op output hook (None clears)."""
    global _debug_hook
    _debug_hook = fn


@jax.jit
def _run_vjp(vjp_fn, cots):
    """Shared jitted pullback runner.

    ``vjp_fn`` is a pytree (its jaxpr lives in the treedef), so jit caches
    one backward executable per distinct forward trace.
    """
    return vjp_fn(cots)


def apply_op(name, fn, args, kwargs, cacheable=True):
    """Run ``fn`` (pure jax) over ``args``/``kwargs`` with Tensors substituted.

    Any ``Tensor`` found anywhere in the (args, kwargs) pytree becomes a
    differentiable input; everything else is closed over as a static attribute.
    Returns Tensor-wrapped outputs mirroring the output pytree of ``fn``.

    ``cacheable=False`` skips the dispatch cache entirely — for callers
    whose ``fn`` is a fresh per-call closure (sparse conv rulebooks):
    their keys would never repeat, so caching only pins the closure's
    captured arrays in the LRU until eviction.
    """
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs), is_leaf=_is_tensor)
    t_pos = [i for i, l in enumerate(leaves) if isinstance(l, Tensor)]
    tensors = [leaves[i] for i in t_pos]
    datas = [t._data for t in tensors]
    from ..amp import amp_cast_inputs
    datas = amp_cast_inputs(name, datas)

    # `pure` is captured by cached jitted executables and by GradNode
    # (primal_fn) — null the Tensor slots so the closure can't pin device
    # buffers or upstream autograd graphs (the slots are overwritten with
    # the call's tdatas anyway).
    base_leaves = list(leaves)
    for i in t_pos:
        base_leaves[i] = None

    def pure(*tdatas):
        new_leaves = list(base_leaves)
        for i, d in zip(t_pos, tdatas):
            new_leaves[i] = d
        a, k = jax.tree_util.tree_unflatten(treedef, new_leaves)
        return fn(*a, **k)

    requires_grad = (mode.is_grad_enabled()
                     and any(not t.stop_gradient for t in tensors))

    # profiler RecordEvent parity: the reference generates a record-event
    # into every ad_func (eager_gen.py "Dygraph Record Event")
    timing = host_events_active()
    t0 = time.perf_counter() if timing else 0.0

    entry = None
    ban_key = None
    if (cacheable and _dispatch_cache_enabled
            and not any(isinstance(d, jax.core.Tracer) for d in datas)):
        key, ban_key = _dispatch_key(name, fn, treedef, leaves, t_pos, datas,
                                     requires_grad)
        if key is not None and ban_key not in _UNJITTABLE_OPS:
            with _DISPATCH_CACHE_LOCK:
                entry = _DISPATCH_CACHE.get(key)
                if entry is None:
                    entry = _CacheEntry()
                    _DISPATCH_CACHE[key] = entry
                    if len(_DISPATCH_CACHE) > _DISPATCH_CACHE_MAX:
                        _DISPATCH_CACHE.popitem(last=False)
                else:
                    _DISPATCH_CACHE.move_to_end(key)

    vjp_fn = None
    compiled = None
    if entry is not None:
        # compile/ban transitions are atomic under the cache lock so two
        # threads on the same key can't duplicate jax.jit wrappers or read
        # a half-cleared entry; the (lazy) jit call itself runs unlocked.
        with _DISPATCH_CACHE_LOCK:
            if (entry.compiled is None and entry.jittable
                    and not entry.banned):
                # second sighting: compile once, reuse forever for this key
                entry.compiled = (jax.jit(lambda *d: jax.vjp(pure, *d))
                                  if requires_grad else jax.jit(pure))
            compiled = entry.compiled
    if compiled is not None:
        try:
            if requires_grad:
                out, raw_vjp = compiled(*datas)
                vjp_fn = lambda cots: _run_vjp(raw_vjp, cots)
            else:
                out = compiled(*datas)
        except Exception as trace_err:
            # ops with value-dependent output shapes (masked_select,
            # nonzero, unique, ...) run eagerly but cannot trace — jax
            # raises at the jit's first call.  Pin this key to the
            # uncached path forever and retry eagerly (a genuine user
            # error will re-raise below with the eager traceback).
            with _DISPATCH_CACHE_LOCK:
                entry.banned = True
                entry.jittable = False
                entry.compiled = None
            vjp_fn = None
            if requires_grad:
                out, vjp_fn = jax.vjp(pure, *datas)
            else:
                out = pure(*datas)
            # eager retry succeeded AND the failure was a jax trace error:
            # this call shape-generalizes to untraceable, so new shapes
            # skip the failed compile (other static-arg/grad combos don't)
            if isinstance(trace_err, _TRACE_ERRORS) and ban_key is not None:
                _UNJITTABLE_OPS.add(ban_key)
    elif requires_grad:
        out, vjp_fn = jax.vjp(pure, *datas)
    else:
        out = pure(*datas)

    if entry is not None and compiled is None:
        # first sighting: mark jittable only if every output leaf is a jax
        # array (ops returning aux python values stay on the uncached path)
        jittable = all(
            isinstance(o, jax.Array)
            for o in jax.tree_util.tree_leaves(out))
        with _DISPATCH_CACHE_LOCK:
            if not entry.banned and entry.compiled is None:
                entry.jittable = jittable

    if timing:
        record_host_event(name, t0, time.perf_counter() - t0)

    out_leaves, out_treedef = jax.tree_util.tree_flatten(out)

    # amp.debugging hook: TensorCheckerConfig + operator stats (reference
    # generates these checks into every ad_func; 5.2).  Registered by
    # amp.debugging on enable so the disabled hot path pays one None check.
    if _debug_hook is not None:
        _debug_hook(name, out_leaves)

    node = None
    if requires_grad:
        avals = [jax.ShapeDtypeStruct(o.shape, o.dtype) for o in out_leaves]
        node = GradNode(name, vjp_fn, tensors, avals, out_treedef,
                        primal_fn=pure,
                        in_dtypes=tuple(d.dtype for d in datas))
        if get_flags("FLAGS_check_nan_inf")["FLAGS_check_nan_inf"]:
            _check_nan_inf(name, out_leaves)

    wrapped = []
    for i, o in enumerate(out_leaves):
        differentiable = requires_grad and jnp.issubdtype(o.dtype, jnp.inexact)
        t = Tensor(o, stop_gradient=not differentiable)
        if differentiable:
            t._node = node
            t._out_idx = i
        wrapped.append(t)
    return jax.tree_util.tree_unflatten(out_treedef, wrapped)


def _check_nan_inf(name, out_leaves):
    """FLAGS_check_nan_inf parity (paddle/fluid/eager/nan_inf_utils.cc)."""
    for o in out_leaves:
        if isinstance(o, jax.core.Tracer):
            return  # cannot check under trace
        if jnp.issubdtype(o.dtype, jnp.inexact) and not bool(jnp.isfinite(o).all()):  # noqa: H001 (tracer-guarded debug check)
            raise FloatingPointError(f"NaN or Inf detected in output of op '{name}'")
