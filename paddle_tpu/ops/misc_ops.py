"""Remaining inventory ops: metrics, normalization variants, losses,
selected-rows/sparse primitives, layout/shape utilities, collective op
names, and registry aliases for creation/random entry points.

Reference locations cited per-op.  This module closes the gap between the
`@op`-registered surface and the YAML op inventory
(paddle/phi/api/yaml/ops.yaml + legacy_ops.yaml; see ops/inventory.py).
"""

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.random import get_rng_key
from .registry import op, register_external, OPS

# ----------------------------------------------------------------- metrics

@op()
def accuracy(x, indices, label):
    """phi accuracy op: x = topk values, indices = topk ids, label [N,1]."""
    lbl = jnp.asarray(label).reshape(-1, 1)
    correct = (jnp.asarray(indices) == lbl).any(axis=1)
    acc = correct.mean(dtype=jnp.float32)
    return (acc, correct.sum().astype(jnp.int32),
            jnp.asarray(lbl.shape[0], jnp.int32))


@op()
def auc(x, label, stat_pos, stat_neg, ins_tag_weight=None, curve="ROC",
        num_thresholds=4095, slide_steps=1):
    """Streaming AUC (phi auc op): bucketed pos/neg histograms."""
    pred = jnp.asarray(x)[:, -1] if jnp.asarray(x).ndim == 2 else \
        jnp.asarray(x).reshape(-1)
    lbl = jnp.asarray(label).reshape(-1)
    bucket = jnp.clip((pred * num_thresholds).astype(jnp.int32), 0,
                      num_thresholds)
    pos_hist = jnp.zeros((num_thresholds + 1,), jnp.int64).at[bucket].add(
        (lbl > 0).astype(jnp.int64))
    neg_hist = jnp.zeros((num_thresholds + 1,), jnp.int64).at[bucket].add(
        (lbl <= 0).astype(jnp.int64))
    sp = stat_pos.reshape(-1)[-(num_thresholds + 1):] + pos_hist
    sn = stat_neg.reshape(-1)[-(num_thresholds + 1):] + neg_hist
    # AUC from histograms (trapezoid over descending thresholds)
    pos_cum = jnp.cumsum(sp[::-1])
    neg_cum = jnp.cumsum(sn[::-1])
    tot_pos = pos_cum[-1]
    tot_neg = neg_cum[-1]
    area = jnp.sum((neg_cum - jnp.concatenate([jnp.zeros(1, jnp.int64),
                                               neg_cum[:-1]]))
                   * (jnp.concatenate([jnp.zeros(1, jnp.int64),
                                       pos_cum[:-1]]) + pos_cum) / 2.0)
    auc_val = jnp.where((tot_pos > 0) & (tot_neg > 0),
                        area / jnp.maximum(tot_pos * tot_neg, 1), 0.0)
    return auc_val.astype(jnp.float32), sp, sn


# ------------------------------------------------------------------ losses

@op()
def bce_loss(input, label):
    x = jnp.clip(input.astype(jnp.float32), 1e-12, 1.0 - 1e-7)
    return -(label * jnp.log(x) + (1 - label) * jnp.log(1 - x))


@op()
def huber_loss(input, label, delta=1.0):
    r = input - label
    ab = jnp.abs(r)
    quad = 0.5 * r * r
    lin = delta * (ab - 0.5 * delta)
    return jnp.where(ab <= delta, quad, lin), r


@op()
def kldiv_loss(x, target, reduction="mean", log_target=False):
    if log_target:
        loss = jnp.exp(target) * (target - x)
    else:
        t = jnp.asarray(target)
        loss = jnp.where(t > 0, t * (jnp.log(jnp.maximum(t, 1e-12)) - x), 0.0)
    if reduction == "mean":
        return loss.mean()
    if reduction == "batchmean":
        return loss.sum() / x.shape[0]
    if reduction == "sum":
        return loss.sum()
    return loss


@op()
def log_loss(input, label, epsilon=1e-4):
    x = input.astype(jnp.float32)
    return -label * jnp.log(x + epsilon) \
        - (1 - label) * jnp.log(1 - x + epsilon)


@op()
def sigmoid_cross_entropy_with_logits(x, label, normalize=False,
                                      ignore_index=-100, pos_weight=None):
    xf = x.astype(jnp.float32)
    l = label.astype(jnp.float32)
    loss = jnp.maximum(xf, 0.0) - xf * l + jnp.log1p(jnp.exp(-jnp.abs(xf)))
    if pos_weight is not None:
        log_w = (pos_weight - 1.0) * l + 1.0
        loss = loss * log_w
    mask = (label != ignore_index)
    loss = jnp.where(mask, loss, 0.0)
    if normalize:
        loss = loss / jnp.maximum(mask.sum().astype(jnp.float32), 1.0)
    return loss


@op()
def cross_entropy_with_softmax(logits, label, soft_label=False,
                               use_softmax=True, numeric_stable_mode=True,
                               ignore_index=-100, axis=-1):
    """phi op: returns (softmax, loss)."""
    lf = logits.astype(jnp.float32)
    sm = jax.nn.softmax(lf, axis=axis)
    logp = jax.nn.log_softmax(lf, axis=axis) if use_softmax else \
        jnp.log(jnp.maximum(lf, 1e-12))
    if soft_label:
        loss = -(label.astype(jnp.float32) * logp).sum(axis=axis,
                                                       keepdims=True)
    else:
        lbl = jnp.asarray(label)
        squeeze = lbl.ndim == logp.ndim
        if squeeze:
            lbl = lbl.squeeze(axis)
        lbl_c = jnp.clip(lbl, 0, logp.shape[axis] - 1)
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(lbl_c, axis), axis=axis)
        loss = -picked
        loss = jnp.where(jnp.expand_dims(lbl, axis) == ignore_index, 0.0,
                         loss)
    return sm, loss


@op()
def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, return_softmax=False,
                         ring_id=-1, rank=0, nranks=1):
    """ArcFace-style margin softmax (paddle/phi/kernels/gpu/
    margin_cross_entropy_kernel.cu; hybrid-parallel variant uses the mp
    group — here single-shard; the TP variant lives in
    fleet.meta_parallel.ParallelCrossEntropy)."""
    lf = logits.astype(jnp.float32)
    lbl = jnp.asarray(label).reshape(-1)
    n, c = lf.shape
    onehot = jax.nn.one_hot(lbl, c, dtype=jnp.float32)
    cos = jnp.clip(lf, -1.0, 1.0)
    theta = jnp.arccos(cos)
    target_cos = jnp.cos(margin1 * theta + margin2) - margin3
    adj = jnp.where(onehot > 0, target_cos, cos) * scale
    logp = jax.nn.log_softmax(adj, axis=-1)
    loss = -(onehot * logp).sum(-1, keepdims=True)
    sm = jnp.exp(logp)
    return loss, sm


@op()
def hsigmoid_loss(x, label, weight, bias=None, num_classes=2,
                  path_table=None, path_code=None, is_sparse=False):
    """Hierarchical sigmoid over the reference's default SimpleCode tree.

    SimpleCode (paddle MatrixBitCodeFunctor): for class c let
    u = c + num_classes; the path visits internal node (u >> (j+1)) - 1
    with sigmoid target bit (u >> j) & 1, for j = 0..bitlen(u)-2.  Using
    the exact reference layout keeps trained hsigmoid weights
    checkpoint-compatible.

    CustomCode (same functor, custom-tree branch): ``path_table[n, j]``
    gives the internal-node row directly and ``path_code[n, j]`` the
    target bit; entries < 0 pad the per-sample path
    (reference: paddle/phi/kernels/funcs/matrix_bit_code.h CustomCode
    calc_index/calc_bit, get_length counts non-negative entries).
    """
    if (path_table is None) != (path_code is None):
        raise ValueError(
            "hsigmoid_loss: path_table and path_code must be given "
            "together (custom tree) or both omitted (SimpleCode)")
    lbl = jnp.asarray(label).reshape(-1)
    if path_table is not None:
        table = jnp.asarray(path_table)
        code = jnp.asarray(path_code)
        valid = table >= 0                                  # [N, L]
        idxs = jnp.clip(table, 0, weight.shape[0] - 1)
        bits = jnp.where(valid, code, 0).astype(jnp.float32)
    else:
        u = lbl + num_classes
        max_len = int(2 * num_classes - 1).bit_length() - 1
        js = jnp.arange(max_len)
        # valid while (u >> (j+1)) > 0 — INTEGER bit length; float32 log2
        # is off-by-one at powers of two and above 2^21 (caught in review)
        valid = (u[:, None] >> (js[None, :] + 1)) > 0          # [N, L]
        idxs = jnp.clip((u[:, None] >> (js[None, :] + 1)) - 1, 0,
                        num_classes - 2)
        bits = ((u[:, None] >> js[None, :]) & 1).astype(jnp.float32)
    w = weight[idxs]  # [N, L, D]
    logit = jnp.einsum("nd,nkd->nk", x.astype(jnp.float32),
                       w.astype(jnp.float32))
    if bias is not None:
        logit = logit + bias.reshape(-1)[idxs]
    loss = jnp.maximum(logit, 0) - logit * bits + \
        jnp.log1p(jnp.exp(-jnp.abs(logit)))
    return jnp.where(valid, loss, 0.0).sum(-1, keepdims=True)


# --------------------------------------------------------- normalization

@op()
def batch_norm_(x, mean, variance, scale=None, bias=None, momentum=0.9,
                epsilon=1e-5, data_format="NCHW", is_test=False,
                use_global_stats=False, trainable_statistics=False):
    """Training batch-norm returning updated running stats (phi batch_norm
    op; reference CPU kernel paddle/phi/kernels/cpu/batch_norm_kernel.cc)."""
    axis = 1 if data_format == "NCHW" else x.ndim - 1
    red = tuple(i for i in range(x.ndim) if i != axis)
    xf = x.astype(jnp.float32)
    if is_test or use_global_stats:
        mu, var = mean, variance
        mean_out, var_out = mean, variance
        saved_mu = jnp.zeros_like(mean)
        saved_var = jnp.zeros_like(variance)
    else:
        mu = xf.mean(red)
        var = xf.var(red)
        mean_out = momentum * mean + (1 - momentum) * mu
        var_out = momentum * variance + (1 - momentum) * var
        saved_mu, saved_var = mu, 1.0 / jnp.sqrt(var + epsilon)
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    out = (xf - mu.reshape(shape)) * \
        jax.lax.rsqrt(var.reshape(shape) + epsilon)
    if scale is not None:
        out = out * scale.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return (out.astype(x.dtype), mean_out, var_out, saved_mu, saved_var)


@op()
def sync_batch_norm_(x, mean, variance, scale=None, bias=None, momentum=0.9,
                     epsilon=1e-5, data_format="NCHW", is_test=False,
                     use_global_stats=False, trainable_statistics=False):
    """Cross-replica BN: inside shard_map/pmap the batch stats are averaged
    over the data-parallel axis; single-process it equals batch_norm_."""
    axis = 1 if data_format == "NCHW" else x.ndim - 1
    red = tuple(i for i in range(x.ndim) if i != axis)
    xf = x.astype(jnp.float32)
    if is_test or use_global_stats:
        return batch_norm_.__wrapped__(x, mean, variance, scale, bias,
                                       momentum, epsilon, data_format,
                                       is_test, use_global_stats,
                                       trainable_statistics)
    mu = xf.mean(red)
    sq = (xf * xf).mean(red)
    try:
        mu = jax.lax.pmean(mu, "dp")
        sq = jax.lax.pmean(sq, "dp")
    except NameError:
        pass
    var = sq - mu * mu
    mean_out = momentum * mean + (1 - momentum) * mu
    var_out = momentum * variance + (1 - momentum) * var
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    out = (xf - mu.reshape(shape)) * jax.lax.rsqrt(
        var.reshape(shape) + epsilon)
    if scale is not None:
        out = out * scale.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return (out.astype(x.dtype), mean_out, var_out, mu,
            1.0 / jnp.sqrt(var + epsilon))


@op()
def spectral_norm(weight, u, v, dim=0, power_iters=1, epsilon=1e-12):
    w = weight.astype(jnp.float32)
    if dim != 0:
        perm = [dim] + [i for i in range(w.ndim) if i != dim]
        w = jnp.transpose(w, perm)
    h = w.shape[0]
    wm = w.reshape(h, -1)
    uu, vv = u.reshape(-1), v.reshape(-1)
    for _ in range(power_iters):
        vv = wm.T @ uu
        vv = vv / (jnp.linalg.norm(vv) + epsilon)
        uu = wm @ vv
        uu = uu / (jnp.linalg.norm(uu) + epsilon)
    sigma = uu @ wm @ vv
    out = (wm / sigma).reshape(w.shape)
    if dim != 0:
        inv = list(np.argsort([dim] + [i for i in range(weight.ndim)
                                       if i != dim]))
        out = jnp.transpose(out, inv)
    return out.astype(weight.dtype)


# ---------------------------------------------------------------- norms

@op()
def p_norm(x, porder=2.0, axis=-1, epsilon=1e-12, keepdim=False,
           asvector=False):
    xf = x.astype(jnp.float32)
    if asvector:
        xf = xf.reshape(-1)
        axis = 0
    if porder == float("inf"):
        out = jnp.abs(xf).max(axis=axis, keepdims=keepdim)
    elif porder == float("-inf"):
        out = jnp.abs(xf).min(axis=axis, keepdims=keepdim)
    elif porder == 0:
        out = (xf != 0).sum(axis=axis, keepdims=keepdim).astype(jnp.float32)
    else:
        out = jnp.power(jnp.power(jnp.abs(xf), porder)
                        .sum(axis=axis, keepdims=keepdim), 1.0 / porder)
    return out.astype(x.dtype)


@op()
def frobenius_norm(x, axis=None, keepdim=False):
    ax = tuple(axis) if axis is not None else None
    return jnp.sqrt(jnp.square(x.astype(jnp.float32))
                    .sum(axis=ax, keepdims=keepdim)).astype(x.dtype)


@op()
def squared_l2_norm(x):
    return jnp.square(x.astype(jnp.float32)).sum().reshape(())


@op()
def clip_by_norm(x, max_norm):
    n = jnp.sqrt(jnp.square(x.astype(jnp.float32)).sum())
    factor = jnp.where(n > max_norm, max_norm / jnp.maximum(n, 1e-12), 1.0)
    return (x.astype(jnp.float32) * factor).astype(x.dtype)


@op()
def renorm(x, p=2.0, axis=0, max_norm=1.0):
    perm_axis = axis if axis >= 0 else x.ndim + axis
    red = tuple(i for i in range(x.ndim) if i != perm_axis)
    norms = jnp.power(jnp.power(jnp.abs(x.astype(jnp.float32)), p)
                      .sum(axis=red, keepdims=True), 1.0 / p)
    factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
    return (x * factor).astype(x.dtype)


# ------------------------------------------------------------ elementwise

@op()
def i0e(x):
    return jax.scipy.special.i0e(x)


@op()
def i1e(x):
    return jax.scipy.special.i1e(x)


@op()
def nextafter(x, y):
    return jnp.nextafter(x, y)


@op()
def logsigmoid(x):
    return jax.nn.log_sigmoid(x)


@op()
def tanh_shrink(x):
    return x - jnp.tanh(x)


@op()
def thresholded_relu(x, threshold=1.0):
    return jnp.where(x > threshold, x, 0.0)


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=True):
    if not training:
        # eval mode: fixed slope on the NEGATIVE part only (reference
        # rrelu_kernel.cc — leaky-relu with slope (lower+upper)/2)
        mid = (lower + upper) / 2.0
        @op("rrelu_eval")
        def _rrelu_eval(x):
            return jnp.where(x >= 0, x, (x.astype(jnp.float32) * mid)
                             .astype(x.dtype))
        return _rrelu_eval(x)
    key = get_rng_key()

    @op("rrelu_train")
    def _rrelu(x):
        a = jax.random.uniform(key, x.shape, jnp.float32, lower, upper)
        return jnp.where(x >= 0, x, (a * x.astype(jnp.float32))
                         .astype(x.dtype))
    return _rrelu(x)


register_external("rrelu", rrelu)


@op()
def elementwise_pow(x, y):
    return jnp.power(x, y)


@op()
def divide_scalar(x, scalar):
    return x / scalar


@op()
def mean_all(x):
    return x.astype(jnp.float32).mean().astype(x.dtype)


# -------------------------------------------------------------- shape ops

@op()
def shape(x):
    return jnp.asarray(x.shape, jnp.int32)


@op()
def reverse(x, axis):
    ax = [axis] if isinstance(axis, int) else list(axis)
    return jnp.flip(x, ax)


@op()
def multiplex(inputs, index):
    stacked = jnp.stack(inputs, 0)  # [K, N, ...]
    idx = jnp.asarray(index).reshape(-1).astype(jnp.int32)
    return stacked[idx, jnp.arange(stacked.shape[1])]


@op()
def split_with_num(x, num, axis=0):
    return jnp.split(x, num, axis=axis)


@op()
def repeat_interleave_with_tensor_index(x, repeats, axis=None):
    reps = jnp.asarray(repeats)
    if isinstance(reps, jax.core.Tracer):
        raise ValueError("tensor repeats requires eager mode (dynamic shape)")
    reps_np = np.asarray(reps)  # noqa: H001 (tracer-guarded, dynamic shape)
    return jnp.repeat(x, reps_np, axis=axis,
                      total_repeat_length=int(reps_np.sum()))  # noqa: H001 (tracer-guarded, dynamic shape)


@op()
def tril_triu(x, diagonal=0, lower=True):
    return jnp.tril(x, diagonal) if lower else jnp.triu(x, diagonal)


@op()
def trans_layout(x, perm):
    return jnp.transpose(x, perm)


@op()
def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    size = index_num // nshards
    lo = shard_id * size
    inside = (input >= lo) & (input < lo + size)
    return jnp.where(inside, input - lo, ignore_value)


@op()
def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1):
    # registered name parity; functional.gumbel_softmax threads rng itself
    g = -jnp.log(-jnp.log(
        jax.random.uniform(jax.random.PRNGKey(0), x.shape) + 1e-20) + 1e-20)
    y = jax.nn.softmax((x + g) / temperature, axis=axis)
    if hard:
        idx = jnp.argmax(y, axis=axis, keepdims=True)
        hard_y = jnp.zeros_like(y).at[...].set(0.0)
        hard_y = jax.nn.one_hot(idx.squeeze(axis), x.shape[axis],
                                axis=axis, dtype=y.dtype)
        y = hard_y + jax.lax.stop_gradient(y) - y
    return y


@op()
def pad3d(x, paddings, mode="constant", value=0.0, data_format="NCDHW"):
    p = [int(v) for v in np.asarray(paddings).reshape(-1)]  # noqa: H001 (padding attrs)
    # paddle order: [left, right, top, bottom, front, back] on (W,H,D)
    if data_format == "NCDHW":
        cfg = [(0, 0), (0, 0), (p[4], p[5]), (p[2], p[3]), (p[0], p[1])]
    else:
        cfg = [(0, 0), (p[4], p[5]), (p[2], p[3]), (p[0], p[1]), (0, 0)]
    modes = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}
    if mode == "constant":
        return jnp.pad(x, cfg, constant_values=value)
    return jnp.pad(x, cfg, mode=modes[mode])


@op()
def full_batch_size_like(input, shape, value, input_dim_idx=0,
                         output_dim_idx=0, dtype=None):
    shp = [int(s) for s in shape]
    shp[output_dim_idx] = input.shape[input_dim_idx]
    return jnp.full(shp, value, dtype=dtype or input.dtype)


@op()
def fill(x, value):
    return jnp.full_like(x, value)


@op()
def fill_diagonal_tensor(x, y, offset=0, dim1=0, dim2=1):
    rows, cols = x.shape[dim1], x.shape[dim2]
    if offset >= 0:
        n = max(min(rows, cols - offset), 0)
    else:
        n = max(min(rows + offset, cols), 0)
    xi = jnp.moveaxis(x, (dim1, dim2), (0, 1))
    idx = jnp.arange(n)
    if offset >= 0:
        xi = xi.at[idx, idx + offset].set(y)
    else:
        xi = xi.at[idx - offset, idx].set(y)
    return jnp.moveaxis(xi, (0, 1), (dim1, dim2))


@op()
def assign_value(shape, dtype, values):
    return jnp.asarray(np.asarray(values).reshape(shape), dtype=dtype)  # noqa: H001 (host literal attr)


@op()
def assign_out_(x, output):
    return x.astype(output.dtype) if hasattr(output, "dtype") else x


@op()
def add_n(inputs):
    out = inputs[0]
    for t in inputs[1:]:
        out = out + t
    return out


@op()
def cast(x, dtype):
    from ..framework.dtype import convert_dtype
    return x.astype(convert_dtype(dtype))


@op()
def copy_to(x, place=None, blocking=True):
    return jnp.asarray(x)


@op()
def npu_identity(x, format=-1):
    return x


@op()
def share_buffer(*xs):
    return tuple(xs) + tuple(jnp.zeros((), jnp.bool_) for _ in xs)


@op()
def coalesce_tensor(inputs, dtype=None, copy_data=True, set_constant=False,
                    persist_output=False, constant=0.0, use_align=True,
                    align_size=-1, size_of_dtype=-1):
    """Flatten a param/grad list into one fused buffer + per-tensor views.

    Reference: paddle/fluid/operators/coalesce_tensor_op.cc — used by
    fused allreduce.  Under XLA the fused buffer is just a concat (the
    compiler already coalesces transfers), so this returns views that
    alias the concatenated flat buffer."""
    flats = [x.reshape(-1) for x in inputs]
    fused = jnp.concatenate(flats)
    if set_constant:
        fused = jnp.full_like(fused, constant)
    outs, off = [], 0
    for x in inputs:
        n = x.size
        outs.append(fused[off:off + n].reshape(x.shape))
        off += n
    return outs, fused


@op()
def merge_selected_rows(rows, values, height=None):
    """SelectedRows (row-sparse gradient) merge: sum duplicate rows.

    The reference's SelectedRows type (paddle/phi/core/selected_rows.h)
    becomes a (rows, values) pair here; embedding-style sparse grads use
    segment_sum which is the TPU-native scatter-add."""
    uniq, inv = jnp.unique(rows, return_inverse=True,
                           size=rows.shape[0], fill_value=-1)
    summed = jax.ops.segment_sum(values, inv.reshape(-1), rows.shape[0])
    return uniq, summed


@op()
def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True):
    """x = packed LU, y = pivots (1-based like LAPACK)."""
    m, n = x.shape[-2], x.shape[-1]
    k = min(m, n)
    l = jnp.tril(x[..., :, :k], -1) + jnp.eye(m, k, dtype=x.dtype)
    u = jnp.triu(x[..., :k, :])
    piv = jnp.asarray(y, jnp.int32) - 1

    def perm_from_pivots(p):
        perm = jnp.arange(m)

        def body(i, perm):
            j = p[i]
            pi, pj = perm[i], perm[j]
            return perm.at[i].set(pj).at[j].set(pi)

        return jax.lax.fori_loop(0, p.shape[-1], body, perm)

    if x.ndim == 2:
        perm = perm_from_pivots(piv)
        pmat = jax.nn.one_hot(perm, m, dtype=x.dtype).T
    else:
        lead = x.shape[:-2]
        pv = piv.reshape((-1, piv.shape[-1]))
        perms = jax.vmap(perm_from_pivots)(pv)
        pmat = jax.vmap(lambda p: jax.nn.one_hot(p, m, dtype=x.dtype).T)(
            perms).reshape(lead + (m, m))
    return pmat, l, u


@op()
def matrix_rank_tol(x, atol_tensor=None, use_default_tol=True,
                    hermitian=False, rtol_tensor=None):
    s = jnp.linalg.svd(x.astype(jnp.float32), compute_uv=False) \
        if not hermitian else jnp.abs(
            jnp.linalg.eigvalsh(x.astype(jnp.float32)))
    smax = s.max(-1, keepdims=True)
    if atol_tensor is not None:
        tol = jnp.asarray(atol_tensor)
        tol = tol.reshape(tol.shape + (1,)) if tol.ndim < s.ndim else tol
    else:
        eps = jnp.finfo(jnp.float32).eps
        tol = max(x.shape[-2], x.shape[-1]) * eps * smax
    return (s > tol).sum(-1).astype(jnp.int64)


@op()
def masked_matmul(x, y, mask):
    """Sparse-masked dense matmul (phi sparse masked_matmul): compute only
    where mask is nonzero — on TPU compute dense (MXU) then mask."""
    out = x.astype(jnp.float32) @ y.astype(jnp.float32)
    return jnp.where(mask != 0, out, 0.0).astype(x.dtype)


# -------------------------------------------------- rng-threading wrappers

def exponential_(x, lam=1.0):
    key = get_rng_key()

    @op("exponential_")
    def _expo(x):
        u = jax.random.uniform(key, x.shape, jnp.float32, 1e-9, 1.0)
        return (-jnp.log(u) / lam).astype(x.dtype)
    out = _expo(x)
    if hasattr(x, "_rebind"):
        x._rebind(out._data)
    return out


register_external("exponential_", exponential_)


def uniform_inplace(x, min=-1.0, max=1.0, seed=0, diag_num=0, diag_step=0,
                    diag_val=1.0):
    key = get_rng_key() if seed == 0 else jax.random.PRNGKey(seed)

    @op("uniform_inplace")
    def _uni(x):
        return jax.random.uniform(key, x.shape, jnp.float32, min, max) \
            .astype(x.dtype)
    out = _uni(x)
    if hasattr(x, "_rebind"):
        x._rebind(out._data)
    return out


register_external("uniform_inplace", uniform_inplace)


def class_center_sample(label, num_classes, num_samples, ring_id=0, rank=0,
                        nranks=1, fix_seed=False, seed=0):
    """Sample negative class centers (PartialFC; paddle/phi/kernels/gpu/
    class_center_sample_kernel.cu)."""
    key = jax.random.PRNGKey(seed) if fix_seed else get_rng_key()

    @op("class_center_sample")
    def _ccs(label):
        lbl = label.reshape(-1)
        pos_mask = jnp.zeros((num_classes,), jnp.bool_).at[lbl].set(True)
        noise = jax.random.uniform(key, (num_classes,))
        # positives first (score 2), then random negatives
        score = jnp.where(pos_mask, 2.0, noise)
        _, sampled = jax.lax.top_k(score, num_samples)
        sampled = jnp.sort(sampled)
        # remap labels into sampled index space
        remap = jnp.full((num_classes,), -1, jnp.int64)
        remap = remap.at[sampled].set(jnp.arange(num_samples, dtype=jnp.int64))
        return remap[lbl], sampled.astype(jnp.int64)
    return _ccs(label)


register_external("class_center_sample", class_center_sample)


def truncated_gaussian_random(shape, mean=0.0, std=1.0, seed=0, a=-2.0,
                              b=2.0, dtype="float32"):
    from ..core.tensor import Tensor
    from ..framework.dtype import convert_dtype
    key = get_rng_key() if seed == 0 else jax.random.PRNGKey(seed)
    out = jax.random.truncated_normal(key, a, b, tuple(shape), jnp.float32)
    return Tensor((out * std + mean).astype(convert_dtype(dtype)))


register_external("truncated_gaussian_random", truncated_gaussian_random)


def dirichlet(alpha):
    key = get_rng_key()

    @op("dirichlet")
    def _dir(alpha):
        return jax.random.dirichlet(key, alpha.astype(jnp.float32))
    return _dir(alpha)


register_external("dirichlet", dirichlet)


# ------------------------------------------------ registry name aliases

def _alias(name, module_attr):
    mod, attr = module_attr
    if name in OPS:
        return
    fn = getattr(mod, attr, None)
    if fn is not None:
        register_external(name, fn)


def _lazy(module_path, fname):
    import importlib

    def f(*a, **k):
        mod = importlib.import_module(module_path, package=__package__)
        return getattr(mod, fname)(*a, **k)
    f.__name__ = fname
    return f


def _register_aliases():
    from . import creation, random as rnd

    for name, target in {
        "arange": (creation, "arange"),
        "empty": (creation, "empty"),
        "eye": (creation, "eye"),
        "full": (creation, "full"),
        "linspace": (creation, "linspace"),
        "logspace": (creation, "logspace"),
        "ones": (creation, "ones"),
        "zeros": (creation, "zeros"),
        "tril_indices": (creation, "tril_indices"),
        "triu_indices": (creation, "triu_indices"),
        "randint": (rnd, "randint"),
        "randperm": (rnd, "randperm"),
        "uniform": (rnd, "uniform"),
        "gaussian": (rnd, "normal"),
    }.items():
        _alias(name, target)

    # lazy: these live in packages imported after ops (avoid import cycles)
    register_external("dropout", _lazy("..nn.functional", "dropout"))

    # in-place creation aliases
    def full_(x, value):
        out = jnp.full_like(x._data if hasattr(x, "_data") else x, value)
        if hasattr(x, "_rebind"):
            return x._rebind(out)
        return out

    register_external("full_", full_)

    def assign_value_(x, values):
        arr = jnp.asarray(
            np.asarray(values)  # noqa: H001 (host literal attr)
        ).reshape(x.shape).astype(x.dtype)
        if hasattr(x, "_rebind"):
            return x._rebind(arr)
        return arr

    register_external("assign_value_", assign_value_)

    # collective op names → communication wrappers (SURVEY §2.6: static
    # graph collective ops lower to XLA collective HLOs; eager wrappers in
    # distributed/communication.py — imported lazily, it loads after ops)
    comm = "..distributed.communication"
    register_external("all_reduce", _lazy(comm, "all_reduce"))
    register_external("all_gather", _lazy(comm, "all_gather"))
    register_external("broadcast", _lazy(comm, "broadcast"))
    register_external("reduce", _lazy(comm, "reduce"))
    register_external("reduce_scatter", _lazy(comm, "reduce_scatter"))
    register_external("p_recv", _lazy(comm, "recv"))
    register_external("p_recv_array", _lazy(comm, "recv"))


_register_aliases()
