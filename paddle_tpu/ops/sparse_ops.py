"""Sparse-tensor primitive ops (phi sparse kernel layer).

Reference: paddle/phi/kernels/sparse/ (COO/CSR conv/matmul/mask, SURVEY
§2.1) and tensor types SparseCooTensor/SparseCsrTensor
(paddle/phi/core/sparse_coo_tensor.h, sparse_csr_tensor.h).

TPU design: sparse storage lives as (indices, values) pairs — dense
gather/scatter/segment ops on the device, matching
``jax.experimental.sparse.BCOO`` layout; the user-level ``paddle_tpu.sparse``
package wraps these in SparseCooTensor/SparseCsrTensor classes.  XLA has no
native sparse HLO, so compute densifies at the op edge (the reference's GPU
kernels do their own gather/scatter too).
"""
# noqa-module: H001 (COO/CSR construction walks host index lists by
# design — dynamic nnz cannot trace; see module docstring)

import jax
import jax.numpy as jnp
import numpy as np

from .registry import op, register_external


@op()
def sparse_coo_tensor(values, indices, shape):
    """Build (indices, values, shape) triple — primitive layer."""
    return (jnp.asarray(indices, jnp.int64), jnp.asarray(values),
            jnp.asarray(np.asarray(shape).reshape(-1), jnp.int64))


@op()
def coalesce(indices, values, shape=None):
    """Sum duplicate coordinates; sorted output (phi CoalesceKernel)."""
    nd, nnz = indices.shape
    if shape is None:
        dims = [int(jnp.max(indices[i])) + 1 for i in range(nd)]
    else:
        dims = [int(s) for s in shape[:nd]]
    strides = np.ones(nd, np.int64)
    for i in range(nd - 2, -1, -1):
        strides[i] = strides[i + 1] * dims[i + 1]
    flat = (indices * jnp.asarray(strides)[:, None]).sum(0)
    uniq, inv = jnp.unique(flat, return_inverse=True, size=nnz,
                           fill_value=-1)
    summed = jax.ops.segment_sum(values, inv.reshape(-1), nnz)
    new_idx = []
    rem = jnp.where(uniq >= 0, uniq, 0)
    for i in range(nd):
        new_idx.append(rem // strides[i])
        rem = rem % strides[i]
    return jnp.stack(new_idx), summed


@op()
def to_dense(indices, values, shape):
    dense = jnp.zeros(tuple(shape) + values.shape[1:], values.dtype)
    return dense.at[tuple(indices[i] for i in range(indices.shape[0]))] \
        .add(values)


def to_sparse_coo(x, sparse_dim=None):
    """Dense → COO (host op in eager: nnz is data-dependent)."""
    from ..core.tensor import Tensor
    arr = np.asarray(x.numpy() if hasattr(x, "numpy") else x)
    sd = sparse_dim or arr.ndim
    flat_tail = arr.reshape(arr.shape[:sd] + (-1,))
    mask = (flat_tail != 0).any(-1).reshape(arr.shape[:sd])
    idx = np.stack(np.nonzero(mask))
    vals = arr[tuple(idx)]
    return (Tensor(jnp.asarray(idx.astype(np.int64))), Tensor(jnp.asarray(vals)),
            tuple(arr.shape))


def csr_crows(rows, nrows, batch=None, nbatch=None):
    """Row pointers in the phi layout: [nrows+1], or for batched CSR the
    per-batch pointers concatenated to [nbatch*(nrows+1)]
    (phi sparse_csr_tensor.h) — the single source for this layout."""
    if batch is None:
        crows = np.zeros(nrows + 1, np.int64)
        np.add.at(crows, np.asarray(rows) + 1, 1)
        return np.cumsum(crows)
    crows = np.zeros((nbatch, nrows + 1), np.int64)
    np.add.at(crows, (np.asarray(batch), np.asarray(rows) + 1), 1)
    return np.cumsum(crows, axis=1).reshape(-1)


def to_sparse_csr(x):
    """Dense 2-D/3-D → CSR (host op).  3-D follows the reference's
    batched-CSR layout (see :func:`csr_crows`)."""
    from ..core.tensor import Tensor
    arr = np.asarray(x.numpy() if hasattr(x, "numpy") else x)
    if arr.ndim == 2:
        rows, cols = np.nonzero(arr)
        vals = arr[rows, cols]
        crows = csr_crows(rows, arr.shape[0])
    elif arr.ndim == 3:
        b, rows, cols = np.nonzero(arr)
        vals = arr[b, rows, cols]
        crows = csr_crows(rows, arr.shape[1], batch=b, nbatch=arr.shape[0])
    else:
        raise ValueError("to_sparse_csr expects a 2-D or 3-D tensor")
    return (Tensor(jnp.asarray(crows)), Tensor(jnp.asarray(cols.astype(np.int64))),
            Tensor(jnp.asarray(vals)), tuple(arr.shape))


@op()
def values(indices, values, shape=None):
    """`.values()` of a sparse tensor — primitive passthrough."""
    return values


register_external("to_sparse_coo", to_sparse_coo)
register_external("to_sparse_csr", to_sparse_csr)
