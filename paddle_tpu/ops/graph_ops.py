"""Graph-NN message-passing ops (paddle.geometric backing kernels).

Reference: paddle/phi/kernels/*/graph_send_recv_*, graph_send_ue_recv,
segment_pool (paddle/phi/kernels/*/segment_pool_*), graph_reindex,
weighted_sample_neighbors (SURVEY §2.9 `paddle.geometric`).

TPU design: everything is a segment reduction (`jax.ops.segment_*`) —
XLA lowers these to sorted scatters that vectorize well.  Sampling /
reindex ops have inherently dynamic output shapes, so they are host ops
(numpy) feeding the input pipeline, like the reference's CPU kernels.
"""
# noqa-module: H001 (sampling/reindex are host ops by design — dynamic
# output shapes cannot trace; see module docstring)

import jax
import jax.numpy as jnp
import numpy as np

from .registry import op, register_external

_REDUCERS = {
    "sum": jax.ops.segment_sum,
    "add": jax.ops.segment_sum,
    "mean": None,  # handled below
    "max": jax.ops.segment_max,
    "min": jax.ops.segment_min,
}


def _segment_reduce(msg, dst, num_segments, reduce_op):
    reduce_op = reduce_op.lower()
    if reduce_op == "mean":
        s = jax.ops.segment_sum(msg, dst, num_segments)
        cnt = jax.ops.segment_sum(jnp.ones((msg.shape[0],), msg.dtype), dst,
                                  num_segments)
        shape = (-1,) + (1,) * (msg.ndim - 1)
        return s / jnp.maximum(cnt.reshape(shape), 1)
    out = _REDUCERS[reduce_op](msg, dst, num_segments)
    if reduce_op in ("max", "min"):
        # empty segments produce +-inf; zero them like the reference
        out = jnp.where(jnp.isfinite(out), out, 0)
    return out


@op()
def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None):
    n = int(out_size) if out_size else x.shape[0]
    msg = x[jnp.asarray(src_index, jnp.int32)]
    return _segment_reduce(msg, jnp.asarray(dst_index, jnp.int32), n,
                           reduce_op)


def _combine(xe, ye, message_op):
    message_op = message_op.lower()
    if message_op in ("add",):
        return xe + ye
    if message_op in ("sub",):
        return xe - ye
    if message_op in ("mul",):
        return xe * ye
    if message_op in ("div",):
        return xe / ye
    raise ValueError(f"unknown message_op {message_op}")


@op()
def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None):
    """x node features, y edge features; message = x[src] (op) y."""
    n = int(out_size) if out_size else x.shape[0]
    xe = x[jnp.asarray(src_index, jnp.int32)]
    msg = _combine(xe, y, message_op)
    return _segment_reduce(msg, jnp.asarray(dst_index, jnp.int32), n,
                           reduce_op)


@op()
def send_uv(x, y, src_index, dst_index, message_op="add"):
    """Per-edge message from both endpoint features (no reduce)."""
    xe = x[jnp.asarray(src_index, jnp.int32)]
    ye = y[jnp.asarray(dst_index, jnp.int32)]
    return _combine(xe, ye, message_op)


def _segment_pool_pure(x, seg, num=0, pool="sum"):
    return _segment_reduce(x, jnp.asarray(seg, jnp.int32), num, pool)


def segment_pool(x, segment_ids, pooltype="SUM"):
    """Segment reduction with the reference's [max_id+1, ...] output
    shape.  The segment count is data-dependent, so it resolves on the
    HOST and rides the dispatch as a static kwarg — the output shape is
    then identical eager, under vjp, and in the cached executable (the
    old in-trace fallback to x.shape[0] silently changed the shape
    whenever the op was traced, caught by the round-4 grad sweep).  The
    module-level pure fn keeps the dispatch cache warm (a per-call
    closure would retrace every step — review regression)."""
    from ..core.tensor import Tensor
    from .dispatch import apply_op

    seg_like = segment_ids._data if isinstance(segment_ids, Tensor) \
        else segment_ids
    if isinstance(seg_like, jax.core.Tracer):
        raise ValueError(
            "segment_pool needs CONCRETE segment_ids (the output shape "
            "is max_id+1, which tracing can't see); under to_static "
            "pass the ids as a python/numpy constant, not a traced "
            "tensor argument")
    seg_np = np.asarray(seg_like).astype(np.int32)
    num = int(seg_np.max()) + 1 if seg_np.size else 0
    return apply_op("segment_pool", _segment_pool_pure,
                    (x, segment_ids),
                    {"num": num, "pool": pooltype.lower()})


register_external("segment_pool", segment_pool)


# ---- host-side (dynamic-output) graph sampling ops ----

def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None):
    """Compact node ids: x = center nodes, neighbors = flat neighbor list.

    Returns (reindexed_src, reindexed_dst, out_nodes); host op.
    """
    x_np = np.asarray(x).reshape(-1)
    nbr = np.asarray(neighbors).reshape(-1)
    cnt = np.asarray(count).reshape(-1)
    out_nodes = list(x_np)
    mapping = {int(v): i for i, v in enumerate(x_np)}
    for v in nbr:
        vi = int(v)
        if vi not in mapping:
            mapping[vi] = len(out_nodes)
            out_nodes.append(vi)
    reindex_src = np.asarray([mapping[int(v)] for v in nbr], np.int64)
    dst = np.repeat(np.arange(len(cnt), dtype=np.int64), cnt)
    from ..core.tensor import Tensor
    return (Tensor(jnp.asarray(reindex_src)), Tensor(jnp.asarray(dst)),
            Tensor(jnp.asarray(np.asarray(out_nodes, np.int64))))


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, return_eids=False):
    """Weighted neighbor sampling on a CSC graph; host op (dynamic shape)."""
    row_np = np.asarray(row).reshape(-1)
    colptr_np = np.asarray(colptr).reshape(-1)
    w_np = np.asarray(edge_weight).reshape(-1)
    nodes = np.asarray(input_nodes).reshape(-1)
    # seed from the paddle.seed-controlled global RNG so sampling varies
    # per call but stays reproducible
    from ..framework.random import get_rng_key
    seed = int(np.asarray(
        jax.random.randint(get_rng_key(), (), 0, np.iinfo(np.int32).max)))
    rng = np.random.RandomState(seed)
    out_nbr, out_cnt, out_eid = [], [], []
    for v in nodes:
        s, e = int(colptr_np[v]), int(colptr_np[v + 1])
        deg = e - s
        if sample_size < 0 or deg <= sample_size:
            sel = np.arange(s, e)
        else:
            p = w_np[s:e].astype(np.float64)
            p = p / p.sum() if p.sum() > 0 else None
            sel = s + rng.choice(deg, size=sample_size, replace=False, p=p)
        out_nbr.extend(row_np[sel])
        out_eid.extend(sel)
        out_cnt.append(len(sel))
    from ..core.tensor import Tensor
    outs = (Tensor(jnp.asarray(np.asarray(out_nbr, np.int64))),
            Tensor(jnp.asarray(np.asarray(out_cnt, np.int64))))
    if return_eids:
        return outs + (Tensor(jnp.asarray(np.asarray(out_eid, np.int64))),)
    return outs


register_external("reindex_graph", reindex_graph)
register_external("weighted_sample_neighbors", weighted_sample_neighbors)
