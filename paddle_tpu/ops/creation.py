"""Tensor creation ops (paddle.tensor.creation parity)."""

import numpy as np

import jax.numpy as jnp

from ..core.tensor import Tensor, to_tensor  # noqa: F401
from ..framework.dtype import convert_dtype, get_default_dtype
from .registry import op


def _dt(dtype, default=None):
    if dtype is None:
        return default if default is not None else get_default_dtype()
    return convert_dtype(dtype)


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(tuple(shape), dtype=_dt(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(tuple(shape), dtype=_dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        # stay on device: jnp.full broadcasts a 0-d fill array, so the
        # value never round-trips through the host (and stays traceable)
        fv = fill_value._data.reshape(())
        return Tensor(jnp.full(tuple(shape), fv,
                               dtype=_dt(dtype, default=fv.dtype)))
    if dtype is None:
        # python-scalar path — the Tensor branch returned above
        dtype = np.asarray(fill_value).dtype  # noqa: H001 (py scalar)
        if dtype == np.float64:
            dtype = get_default_dtype()
    return Tensor(jnp.full(tuple(shape), fill_value, dtype=_dt(dtype)))


def empty(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(tuple(shape), dtype=_dt(dtype)))


def arange(start=0, end=None, step=1, dtype=None, name=None):
    for v in (start, end, step):
        if isinstance(v, Tensor):
            raise TypeError("arange takes python scalars")
    if end is None:
        start, end = 0, start
    if dtype is None:
        if all(isinstance(v, (int, np.integer)) for v in (start, end, step)):
            dtype = "int64"
        else:
            dtype = get_default_dtype()
    return Tensor(jnp.arange(start, end, step, dtype=_dt(dtype)))


def linspace(start, stop, num, dtype=None, name=None):
    return Tensor(jnp.linspace(float(start), float(stop), int(num), dtype=_dt(dtype)))  # noqa: H001 (scalar args by contract)


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(jnp.logspace(float(start), float(stop), int(num), base=base,  # noqa: H001 (scalar args by contract)
                               dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)))


@op()
def zeros_like(x, dtype=None):
    return jnp.zeros_like(x, dtype=convert_dtype(dtype))


@op()
def ones_like(x, dtype=None):
    return jnp.ones_like(x, dtype=convert_dtype(dtype))


@op()
def full_like(x, fill_value, dtype=None):
    return jnp.full_like(x, fill_value, dtype=convert_dtype(dtype))


@op()
def empty_like(x, dtype=None):
    return jnp.zeros_like(x, dtype=convert_dtype(dtype))


@op()
def diag(x, offset=0, padding_value=0):
    if x.ndim == 1 and padding_value != 0:
        out = jnp.diag(x, k=offset)
        mask = jnp.diag(jnp.ones_like(x, dtype=bool), k=offset)
        return jnp.where(mask, out, jnp.asarray(padding_value, dtype=out.dtype))
    return jnp.diag(x, k=offset)


@op()
def diagflat(x, offset=0):
    return jnp.diagflat(x, k=offset)


@op()
def diag_embed(x, offset=0, dim1=-2, dim2=-1):
    n = x.shape[-1] + abs(offset)
    base = jnp.zeros(x.shape[:-1] + (n, n), dtype=x.dtype)
    r = jnp.arange(x.shape[-1])
    rows = r - offset if offset < 0 else r
    cols = r + offset if offset > 0 else r
    out = base.at[..., rows, cols].set(x)
    nd = out.ndim
    d1, d2 = dim1 % nd, dim2 % nd
    if (d1, d2) != (nd - 2, nd - 1):
        perm = [a for a in range(nd) if a not in (d1, d2)]
        perm.insert(d1, nd - 2)
        inv = list(range(nd))
        src = [a for a in range(nd - 2)]
        # move the last two axes into positions (d1, d2)
        order = []
        rest = iter(range(nd - 2))
        for a in range(nd):
            if a == d1:
                order.append(nd - 2)
            elif a == d2:
                order.append(nd - 1)
            else:
                order.append(next(rest))
        out = jnp.transpose(out, order)
    return out


@op()
def tril(x, diagonal=0):
    return jnp.tril(x, k=diagonal)


@op()
def triu(x, diagonal=0):
    return jnp.triu(x, k=diagonal)


def tril_indices(row, col=None, offset=0, dtype="int64"):
    if col is None:
        col = row
    r, c = jnp.tril_indices(row, k=offset, m=col)
    return Tensor(jnp.stack([r, c]).astype(convert_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    if col is None:
        col = row
    r, c = jnp.triu_indices(row, k=offset, m=col)
    return Tensor(jnp.stack([r, c]).astype(convert_dtype(dtype)))


@op()
def meshgrid(*args):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    return tuple(jnp.meshgrid(*args, indexing="ij"))


@op()
def assign(x, output=None):
    return jnp.asarray(x)


@op()
def complex(real, imag):
    from jax import lax
    return lax.complex(real, imag)


@op()
def polar(abs, angle):
    from jax import lax
    return lax.complex(abs * jnp.cos(angle), abs * jnp.sin(angle))


@op()
def clone(x):
    return jnp.array(x, copy=True)


@op()
def numel(x):
    return jnp.asarray(np.prod(x.shape) if x.shape else 1, dtype=jnp.int64)
