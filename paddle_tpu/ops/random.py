"""Random ops over the global RNG (paddle.tensor.random parity).

Eager calls split the global key (framework/random.py).  Under jit these would
bake a constant key — jit training paths must thread keys explicitly (the
nn.functional dropout and train-step helpers accept a key).
"""

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..framework.dtype import convert_dtype, get_default_dtype
from ..framework.random import get_rng_key
from .registry import op


def _shape(shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()  # noqa: H001 (concrete shape required)
    return tuple(int(s) for s in shape)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    dtype = convert_dtype(dtype) if dtype else get_default_dtype()
    key = jax.random.PRNGKey(seed) if seed else get_rng_key()
    return Tensor(jax.random.uniform(key, _shape(shape), dtype=dtype,
                                     minval=min, maxval=max))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._data if isinstance(mean, Tensor) else mean
        s = std._data if isinstance(std, Tensor) else std
        out_shape = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        return Tensor(m + s * jax.random.normal(get_rng_key(), out_shape,
                                                dtype=get_default_dtype()))
    return Tensor(mean + std * jax.random.normal(get_rng_key(), _shape(shape or []),
                                                 dtype=get_default_dtype()))


def standard_normal(shape, dtype=None, name=None):
    dtype = convert_dtype(dtype) if dtype else get_default_dtype()
    return Tensor(jax.random.normal(get_rng_key(), _shape(shape), dtype=dtype))


def randn(shape, dtype=None, name=None):
    return standard_normal(shape, dtype=dtype)


def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype=dtype, min=0.0, max=1.0)


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(get_rng_key(), _shape(shape), low, high,
                                     dtype=convert_dtype(dtype)))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    if high is None:
        low, high = 0, low
    dtype = convert_dtype(dtype) if dtype else x.dtype
    return Tensor(jax.random.randint(get_rng_key(), tuple(x.shape), low, high,
                                     dtype=dtype))


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(get_rng_key(), n).astype(convert_dtype(dtype)))


@op()
def bernoulli(x):
    return jax.random.bernoulli(get_rng_key(), x).astype(x.dtype)


@op()
def multinomial(x, num_samples=1, replacement=False):
    key = get_rng_key()
    logits = jnp.log(jnp.maximum(x, 1e-38))
    if x.ndim == 1:
        if replacement:
            return jax.random.categorical(key, logits, shape=(num_samples,)).astype(jnp.int64)
        return jax.random.choice(key, x.shape[0], shape=(num_samples,),
                                 replace=False, p=x / jnp.sum(x)).astype(jnp.int64)
    keys = jax.random.split(key, x.shape[0])
    if replacement:
        return jax.vmap(lambda k, lg: jax.random.categorical(k, lg, shape=(num_samples,)))(
            keys, logits).astype(jnp.int64)
    return jax.vmap(lambda k, p: jax.random.choice(k, x.shape[1], shape=(num_samples,),
                                                   replace=False, p=p / jnp.sum(p)))(
        keys, x).astype(jnp.int64)


@op()
def poisson(x):
    return jax.random.poisson(get_rng_key(), x).astype(x.dtype)


def rand_like(x, dtype=None):
    dtype = convert_dtype(dtype) if dtype else x.dtype
    return Tensor(jax.random.uniform(get_rng_key(), tuple(x.shape), dtype=dtype))


def normal_like(x, mean=0.0, std=1.0):
    return Tensor(mean + std * jax.random.normal(get_rng_key(), tuple(x.shape),
                                                 dtype=x.dtype))
