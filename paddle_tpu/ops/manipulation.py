"""Shape/layout/indexing manipulation ops (paddle.tensor.manipulation parity).

Ops with data-dependent output shapes (unique, nonzero, masked_select) work in
eager mode but cannot be traced under jit — same restriction as jax; the
reference runs them host-side too.
"""

import jax
import jax.numpy as jnp
from jax import lax

from .registry import op


@op()
def reshape(x, shape):
    return jnp.reshape(x, tuple(int(s) for s in shape))

@op()
def transpose(x, perm):
    return jnp.transpose(x, axes=perm)

@op()
def t(x):
    return x.T

@op()
def moveaxis(x, source, destination):
    return jnp.moveaxis(x, source, destination)

@op()
def concat(x, axis=0):
    return jnp.concatenate(x, axis=int(axis))

@op()
def stack(x, axis=0):
    return jnp.stack(x, axis=axis)

@op()
def unstack(x, axis=0, num=None):
    n = num if num is not None else x.shape[axis]
    return tuple(jnp.squeeze(s, axis=axis)
                 for s in jnp.split(x, n, axis=axis))

unbind = unstack

@op()
def split(x, num_or_sections, axis=0):
    axis = int(axis)
    if isinstance(num_or_sections, int):
        return tuple(jnp.split(x, num_or_sections, axis=axis))
    sections = list(num_or_sections)
    # paddle allows one -1 section meaning "the rest"
    if -1 in sections:
        known = sum(s for s in sections if s != -1)
        sections[sections.index(-1)] = x.shape[axis] - known
    offsets = []
    acc = 0
    for s in sections[:-1]:
        acc += s
        offsets.append(acc)
    return tuple(jnp.split(x, offsets, axis=axis))

@op()
def chunk(x, chunks, axis=0):
    return tuple(jnp.array_split(x, chunks, axis=axis))

@op()
def squeeze(x, axis=None):
    if axis is None:
        return jnp.squeeze(x)
    if isinstance(axis, (list, tuple)):
        axis = tuple(a for a in axis if x.shape[a] == 1)
        return jnp.squeeze(x, axis=axis) if axis else x
    return jnp.squeeze(x, axis=axis) if x.shape[axis] == 1 else x

@op()
def unsqueeze(x, axis):
    if isinstance(axis, (list, tuple)):
        return jnp.expand_dims(x, axis=tuple(axis))
    return jnp.expand_dims(x, axis=axis)

@op()
def flatten(x, start_axis=0, stop_axis=-1):
    nd = x.ndim
    if nd == 0:
        return x.reshape(1)
    start = start_axis % nd
    stop = stop_axis % nd
    shape = x.shape[:start] + (-1,) + x.shape[stop + 1:]
    return x.reshape(shape)

@op()
def flip(x, axis):
    return jnp.flip(x, axis=axis)

@op()
def rot90(x, k=1, axes=(0, 1)):
    return jnp.rot90(x, k=k, axes=tuple(axes))

@op()
def roll(x, shifts, axis=None):
    return jnp.roll(x, shifts, axis=axis)

@op()
def tile(x, repeat_times):
    return jnp.tile(x, tuple(repeat_times))

@op()
def expand(x, shape):
    shape = list(shape)
    # paddle: -1 keeps the original dim
    offset = len(shape) - x.ndim
    for i in range(len(shape)):
        if shape[i] == -1:
            shape[i] = x.shape[i - offset]
    return jnp.broadcast_to(x, tuple(shape))

@op()
def expand_as(x, y):
    return jnp.broadcast_to(x, y.shape)

@op()
def broadcast_to(x, shape):
    return jnp.broadcast_to(x, tuple(shape))

@op()
def broadcast_tensors(inputs):
    return tuple(jnp.broadcast_arrays(*inputs))

@op()
def gather(x, index, axis=0):
    index = index.reshape(-1) if index.ndim > 1 else index
    return jnp.take(x, index, axis=axis)

@op()
def gather_nd(x, index):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx]

@op()
def scatter(x, index, updates, overwrite=True):
    """Row scatter (paddle.scatter: index over dim 0)."""
    index = index.reshape(-1)
    if overwrite:
        return x.at[index].set(updates)
    # paddle accumulate mode: zero out target rows then add
    zeroed = x.at[index].set(jnp.zeros_like(updates))
    return zeroed.at[index].add(updates)

@op()
def scatter_nd_add(x, index, updates):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(updates)

@op()
def scatter_nd(index, updates, shape):
    zeros = jnp.zeros(tuple(shape), dtype=updates.dtype)
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return zeros.at[idx].add(updates)

@op()
def index_select(x, index, axis=0):
    return jnp.take(x, index.reshape(-1), axis=axis)

@op()
def index_add(x, index, axis, value):
    moved = jnp.moveaxis(x, axis, 0)
    out = moved.at[index.reshape(-1)].add(jnp.moveaxis(value, axis, 0))
    return jnp.moveaxis(out, 0, axis)

@op()
def index_put(x, indices, value, accumulate=False):
    idx = tuple(i for i in indices)
    if accumulate:
        return x.at[idx].add(value)
    return x.at[idx].set(value)

@op()
def index_sample(x, index):
    rows = jnp.arange(x.shape[0])[:, None]
    return x[rows, index]

@op()
def take_along_axis(arr, indices, axis, broadcast=True):
    if broadcast:
        shape = list(arr.shape)
        shape[axis] = indices.shape[axis]
        indices = jnp.broadcast_to(indices, tuple(shape))
    return jnp.take_along_axis(arr, indices, axis=axis)

@op()
def put_along_axis(arr, indices, values, axis, reduce="assign"):
    values = jnp.broadcast_to(values, indices.shape) if jnp.ndim(values) else \
        jnp.full(indices.shape, values, dtype=arr.dtype)
    if reduce == "assign":
        return jnp.put_along_axis(arr, indices, values, axis=axis, inplace=False)
    moved_i = jnp.moveaxis(indices, axis, 0)
    moved_a = jnp.moveaxis(arr, axis, 0)
    moved_v = jnp.moveaxis(values, axis, 0)
    grid = jnp.indices(moved_i.shape)
    idx = (moved_i,) + tuple(grid[1:])
    if reduce == "add":
        out = moved_a.at[idx].add(moved_v)
    elif reduce == "multiply" or reduce == "mul":
        out = moved_a.at[idx].multiply(moved_v)
    else:
        raise ValueError(f"unsupported reduce {reduce!r}")
    return jnp.moveaxis(out, 0, axis)

@op()
def masked_select(x, mask):
    return x[mask]

@op()
def masked_fill(x, mask, value):
    return jnp.where(mask, jnp.asarray(value, dtype=x.dtype), x)

@op()
def where(condition, x=None, y=None):
    if x is None and y is None:
        return jnp.nonzero(condition)
    return jnp.where(condition, x, y)

@op()
def select_scatter(x, values, axis, index):
    import builtins
    # builtins.slice: the module-global ``slice`` is the op wrapper below
    ax = axis % x.ndim  # negative axis must index from the back, not axis 0
    return x.at[(builtins.slice(None),) * ax + (index,)].set(values)

@op()
def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None):
    res = jnp.unique(x, return_index=return_index, return_inverse=return_inverse,
                     return_counts=return_counts, axis=axis)
    return res

@op()
def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None):
    import numpy as np
    import jax
    if isinstance(jnp.asarray(x), jax.core.Tracer):
        raise ValueError(
            "unique_consecutive requires eager mode (dynamic shape)")
    xs = np.asarray(x)  # noqa: H001 (tracer-guarded, dynamic shape)
    if axis is None:
        xs = xs.reshape(-1)
        keep = np.concatenate([[True], xs[1:] != xs[:-1]])
    else:
        moved = np.moveaxis(xs, axis, 0)
        flat = moved.reshape(moved.shape[0], -1)
        keep = np.concatenate([[True], np.any(flat[1:] != flat[:-1], axis=1)])
        out = np.moveaxis(np.moveaxis(xs, axis, 0)[keep], 0, axis)
        return jnp.asarray(out)
    out = [jnp.asarray(xs[keep])]
    if return_inverse:
        out.append(jnp.asarray(np.cumsum(keep) - 1))
    if return_counts:
        idx = np.flatnonzero(keep)
        out.append(jnp.asarray(np.diff(np.append(idx, xs.size))))
    return out[0] if len(out) == 1 else tuple(out)

@op()
def sort(x, axis=-1, descending=False, stable=False):
    out = jnp.sort(x, axis=axis, stable=stable)
    return jnp.flip(out, axis=axis) if descending else out

@op()
def argsort(x, axis=-1, descending=False, stable=False):
    out = jnp.argsort(x, axis=axis, stable=stable)
    return jnp.flip(out, axis=axis) if descending else out

@op()
def topk(x, k, axis=-1, largest=True, sorted=True):
    axis = axis % x.ndim
    moved = jnp.moveaxis(x, axis, -1)
    if largest:
        vals, idx = lax.top_k(moved, k)
    else:
        vals, idx = lax.top_k(-moved, k)
        vals = -vals
    return jnp.moveaxis(vals, -1, axis), jnp.moveaxis(idx, -1, axis)

@op()
def kthvalue(x, k, axis=-1, keepdim=False):
    axis = axis % x.ndim
    vals = jnp.sort(x, axis=axis)
    idxs = jnp.argsort(x, axis=axis)
    v = jnp.take(vals, k - 1, axis=axis)
    i = jnp.take(idxs, k - 1, axis=axis)
    if keepdim:
        v = jnp.expand_dims(v, axis)
        i = jnp.expand_dims(i, axis)
    return v, i

@op("mode")
def mode_(x, axis=-1, keepdim=False):
    axis = axis % x.ndim
    moved = jnp.moveaxis(x, axis, -1)
    srt = jnp.sort(moved, axis=-1)
    n = srt.shape[-1]
    runs = jnp.concatenate(
        [jnp.ones(srt.shape[:-1] + (1,), bool), srt[..., 1:] != srt[..., :-1]], -1)
    run_id = jnp.cumsum(runs, axis=-1)
    counts = jax.vmap(lambda rid: jnp.bincount(rid, length=n + 1))(
        run_id.reshape(-1, n)).reshape(run_id.shape[:-1] + (n + 1,))
    cnt_per_elem = jnp.take_along_axis(counts, run_id, axis=-1)
    best = jnp.argmax(cnt_per_elem, axis=-1)
    mode_vals = jnp.take_along_axis(srt, best[..., None], axis=-1)[..., 0]
    eq = moved == mode_vals[..., None]
    first_idx = jnp.argmax(eq, axis=-1)
    if keepdim:
        mode_vals = jnp.expand_dims(mode_vals, axis)
        first_idx = jnp.expand_dims(first_idx, axis)
    return mode_vals, first_idx

@op()
def argmax(x, axis=None, keepdim=False, dtype="int64"):
    out = jnp.argmax(x, axis=axis, keepdims=keepdim if axis is not None else False)
    return out

@op()
def argmin(x, axis=None, keepdim=False, dtype="int64"):
    return jnp.argmin(x, axis=axis, keepdims=keepdim if axis is not None else False)

@op()
def nonzero(x, as_tuple=False):
    res = jnp.nonzero(x)
    if as_tuple:
        return tuple(r[:, None] for r in res)
    return jnp.stack(res, axis=1)

@op()
def searchsorted(sorted_sequence, values, out_int32=False, right=False):
    side = "right" if right else "left"
    if sorted_sequence.ndim == 1:
        out = jnp.searchsorted(sorted_sequence, values, side=side)
    else:
        out = jax.vmap(lambda s, v: jnp.searchsorted(s, v, side=side))(
            sorted_sequence.reshape(-1, sorted_sequence.shape[-1]),
            values.reshape(-1, values.shape[-1]))
        out = out.reshape(values.shape)
    return out.astype(jnp.int32) if out_int32 else out

@op()
def bucketize(x, sorted_sequence, out_int32=False, right=False):
    side = "right" if right else "left"
    out = jnp.searchsorted(sorted_sequence, x, side=side)
    return out.astype(jnp.int32) if out_int32 else out

@op()
def repeat_interleave(x, repeats, axis=None):
    return jnp.repeat(x, repeats, axis=axis)

@op()
def slice(x, axes, starts, ends):
    import builtins
    idx = [builtins.slice(None)] * x.ndim
    for ax, st, en in zip(axes, starts, ends):
        idx[ax] = builtins.slice(int(st), int(en))  # noqa: H001 (int attrs by contract)
    return x[tuple(idx)]

@op()
def strided_slice(x, axes, starts, ends, strides):
    import builtins
    idx = [builtins.slice(None)] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        idx[ax] = builtins.slice(int(st), int(en), int(sd))  # noqa: H001 (int attrs by contract)
    return x[tuple(idx)]

@op()
def crop(x, shape, offsets=None):
    if offsets is None:
        offsets = [0] * x.ndim
    import builtins
    idx = tuple(builtins.slice(int(o), int(o) + int(s)) for o, s in zip(offsets, shape))
    return x[idx]

@op()
def as_complex(x):
    return lax.complex(x[..., 0], x[..., 1])

@op()
def as_real(x):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)

@op()
def view_as(x, other):
    return x.reshape(other.shape)

@op()
def diagonal(x, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)

@op()
def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1):
    d = jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)
    nd = x.ndim
    ax1, ax2 = axis1 % nd, axis2 % nd
    perm = [a for a in range(nd) if a not in (ax1, ax2)] + [ax1, ax2]
    xt = jnp.transpose(x, perm)
    r = jnp.arange(d.shape[-1])
    rows = r - offset if offset < 0 else r
    cols = r + offset if offset > 0 else r
    xt = xt.at[..., rows, cols].set(jnp.asarray(y))
    inv = [0] * nd
    for i2, p in enumerate(perm):
        inv[p] = i2
    return jnp.transpose(xt, inv)

@op()
def fill_diagonal(x, value, offset=0, wrap=False):
    n = min(x.shape[-2], x.shape[-1])
    r = jnp.arange(n - abs(offset) if offset else n)
    rows = r - offset if offset < 0 else r
    cols = r + offset if offset > 0 else r
    return x.at[..., rows, cols].set(value)

@op()
def tensordot(x, y, axes=2):
    return jnp.tensordot(x, y, axes=axes)

@op()
def atleast_1d(x):
    return jnp.atleast_1d(x)

@op()
def atleast_2d(x):
    return jnp.atleast_2d(x)

@op()
def atleast_3d(x):
    return jnp.atleast_3d(x)
