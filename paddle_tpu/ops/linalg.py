"""Linear algebra ops (paddle.tensor.linalg parity).

matmul is the MXU hot path: shapes stay static, bf16 inputs hit the systolic
array directly (reference counterpart: phi::MatmulKernel at
paddle/phi/kernels/gpu/matmul_kernel.cu:22 → cuBLAS; here → XLA dot_general).
"""

import jax
import jax.numpy as jnp
from jax import lax

from .registry import op


@op()
def matmul(x, y, transpose_x=False, transpose_y=False):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    return jnp.matmul(x, y)


@op()
def bmm(x, y):
    return jnp.matmul(x, y)


@op()
def mm(x, y):
    return jnp.matmul(x, y)


@op()
def dot(x, y):
    return jnp.sum(x * y, axis=-1)


@op()
def mv(x, vec):
    return jnp.matmul(x, vec)


@op()
def addmm(input, x, y, beta=1.0, alpha=1.0):
    return beta * input + alpha * jnp.matmul(x, y)


@op()
def multi_dot(x):
    return jnp.linalg.multi_dot(x)


@op()
def norm(x, p="fro", axis=None, keepdim=False):
    if axis is None:
        flat = x.reshape(-1)
        if p in ("fro", 2):
            return jnp.linalg.norm(flat, ord=2, keepdims=False)
        if p == jnp.inf or p == float("inf"):
            return jnp.max(jnp.abs(flat))
        if p == -jnp.inf or p == float("-inf"):
            return jnp.min(jnp.abs(flat))
        if p == 0:
            return jnp.sum(flat != 0).astype(x.dtype)
        return jnp.sum(jnp.abs(flat) ** p) ** (1.0 / p)
    if isinstance(axis, (list, tuple)) and len(axis) == 2:
        ord_ = "fro" if p == "fro" else p
        return jnp.linalg.norm(x, ord=ord_, axis=tuple(axis), keepdims=keepdim)
    if p == "fro":
        p = 2
    return jnp.linalg.norm(x, ord=p, axis=axis, keepdims=keepdim)


@op()
def dist(x, y, p=2.0):
    d = (x - y).reshape(-1)
    if p == 0:
        return jnp.sum(d != 0).astype(d.dtype)
    if p == float("inf"):
        return jnp.max(jnp.abs(d))
    if p == float("-inf"):
        return jnp.min(jnp.abs(d))
    return jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)


@op()
def cross(x, y, axis=9):
    axis = -1 if axis == 9 else axis
    return jnp.cross(x, y, axis=axis)


@op()
def cholesky(x, upper=False):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2).conj() if upper else L


@op()
def cholesky_solve(x, y, upper=False):
    # solve A z = x given cholesky factor y of A
    fac = y if not upper else jnp.swapaxes(y, -1, -2).conj()
    z = jax.scipy.linalg.solve_triangular(fac, x, lower=True)
    return jax.scipy.linalg.solve_triangular(
        jnp.swapaxes(fac, -1, -2).conj(), z, lower=False)


@op()
def inverse(x):
    return jnp.linalg.inv(x)


@op()
def det(x):
    return jnp.linalg.det(x)


@op()
def slogdet(x):
    sign, logdet = jnp.linalg.slogdet(x)
    return jnp.stack([sign, logdet])


@op()
def svd(x, full_matrices=False):
    return jnp.linalg.svd(x, full_matrices=full_matrices)


@op()
def svdvals(x):
    return jnp.linalg.svd(x, compute_uv=False)


@op()
def qr(x, mode="reduced"):
    return jnp.linalg.qr(x, mode=mode)


@op()
def eig(x):
    # XLA supports eig on CPU only; same restriction as reference GPU fallback
    return jnp.linalg.eig(x)


@op()
def eigh(x, UPLO="L"):
    return jnp.linalg.eigh(x, UPLO=UPLO)


@op()
def eigvals(x):
    return jnp.linalg.eigvals(x)


@op()
def eigvalsh(x, UPLO="L"):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


@op()
def pinv(x, rcond=1e-15, hermitian=False):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


@op()
def solve(x, y):
    return jnp.linalg.solve(x, y)


@op()
def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False):
    return jax.scipy.linalg.solve_triangular(
        x, y, lower=not upper, trans=1 if transpose else 0,
        unit_diagonal=unitriangular)


@op()
def lu(x, pivot=True):
    lu_mat, piv = jax.scipy.linalg.lu_factor(x)
    return lu_mat, piv.astype(jnp.int32) + 1  # paddle pivots are 1-based


@op()
def lstsq(x, y, rcond=None, driver=None):
    sol, res, rank, sv = jnp.linalg.lstsq(x, y, rcond=rcond)
    return sol, res, rank, sv


@op()
def matrix_power(x, n):
    return jnp.linalg.matrix_power(x, n)


@op()
def matrix_rank(x, tol=None, hermitian=False):
    return jnp.linalg.matrix_rank(x, rtol=tol)


@op()
def cond(x, p=None):
    return jnp.linalg.cond(x, p=p)


@op()
def einsum(equation, *operands):
    if len(operands) == 1 and isinstance(operands[0], (list, tuple)):
        operands = tuple(operands[0])
    return jnp.einsum(equation, *operands)


@op()
def histogram(x, bins=100, min=0, max=0, weight=None, density=False):
    if min == 0 and max == 0:
        range_ = None
    else:
        range_ = (min, max)
    hist, _ = jnp.histogram(x.reshape(-1), bins=bins, range=range_,
                            weights=None if weight is None else weight.reshape(-1),
                            density=density)
    return hist if density or weight is not None else hist.astype(jnp.int64)


@op()
def bincount(x, weights=None, minlength=0):
    length = int(max(minlength, int(jnp.max(x)) + 1 if x.size else minlength))  # noqa: H001 (data-dependent length, eager-only)
    return jnp.bincount(x, weights=weights, length=max(length, 1))


@op()
def corrcoef(x, rowvar=True):
    return jnp.corrcoef(x, rowvar=rowvar)


@op()
def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0,
                   fweights=fweights, aweights=aweights)


@op()
def trace(x, offset=0, axis1=0, axis2=1):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


@op()
def matrix_transpose(x):
    return jnp.swapaxes(x, -1, -2)


@op()
def householder_product(x, tau):
    *batch, m, n = x.shape

    def single(a, t):
        q = jnp.eye(m, dtype=x.dtype)
        def body(i, q):
            v = jnp.where(jnp.arange(m) < i, 0.0, a[:, i])
            v = v.at[i].set(1.0)
            h = jnp.eye(m, dtype=x.dtype) - t[i] * jnp.outer(v, v)
            return q @ h
        q = lax.fori_loop(0, n, body, q)
        return q[:, :n]

    if batch:
        flat_x = x.reshape((-1, m, n))
        flat_t = tau.reshape((-1, tau.shape[-1]))
        out = jax.vmap(single)(flat_x, flat_t)
        return out.reshape(tuple(batch) + (m, n))
    return single(x, tau)
