"""Op library: registry + eager dispatch + Tensor method patching.

Patching operator methods onto Tensor mirrors the reference's
``monkey_patch_math_tensor`` (python/paddle/fluid/dygraph/math_op_patch.py).
"""

from . import creation, linalg, logic, manipulation, math, random  # noqa: F401
from . import (  # noqa: F401
    conv_extra,
    fft_ops,
    fused_ops,
    graph_ops,
    misc_ops,
    optim_ops,
    pool_ops,
    seq_ops,
    sparse_ops,
    vision_ops,
)
from .dispatch import (  # noqa: F401
    apply_op,
    dispatch_cache_clear,
    dispatch_cache_info,
    enable_dispatch_cache,
)
from .registry import OPS, coverage, op, raw  # noqa: F401
from ..core.tensor import Tensor


def _u(name):
    return OPS[name].user_fn


# aliases: same op, second paddle-facing name
for _alias, _orig in [("unbind", "unstack"), ("remainder", "mod"),
                      ("inv", "inverse")]:
    if _orig in OPS and _alias not in OPS:
        OPS[_alias] = OPS[_orig]


_BINARY_DUNDERS = {
    "__add__": "add", "__radd__": "add",
    "__sub__": "subtract",
    "__mul__": "multiply", "__rmul__": "multiply",
    "__truediv__": "divide",
    "__floordiv__": "floor_divide",
    "__mod__": "mod",
    "__pow__": "pow",
    "__matmul__": "matmul",
    "__eq__": "equal", "__ne__": "not_equal",
    "__lt__": "less_than", "__le__": "less_equal",
    "__gt__": "greater_than", "__ge__": "greater_equal",
    "__and__": "bitwise_and", "__or__": "bitwise_or",
    "__xor__": "bitwise_xor",
}

_REFLECTED = {
    "__rsub__": "subtract",
    "__rtruediv__": "divide",
    "__rpow__": "pow",
    "__rfloordiv__": "floor_divide",
    "__rmod__": "mod",
    "__rmatmul__": "matmul",
}

# Tensor.<method> -> op name (method signature == op signature minus leading x)
_METHODS = [
    "add", "subtract", "multiply", "divide", "floor_divide", "mod", "pow",
    "maximum", "minimum", "fmax", "fmin", "atan2", "logaddexp",
    "sqrt", "rsqrt", "exp", "expm1", "log", "log2", "log10", "log1p",
    "abs", "neg", "sign", "floor", "ceil", "round", "trunc", "frac",
    "sin", "cos", "tan", "asin", "acos", "atan", "sinh", "cosh", "tanh",
    "asinh", "acosh", "atanh", "reciprocal", "square", "erf", "erfinv",
    "digamma", "lgamma", "logit", "sigmoid", "angle", "conj", "real", "imag",
    "nan_to_num", "clip", "scale", "lerp", "increment",
    "sum", "nansum", "mean", "nanmean", "prod", "max", "min", "amax", "amin",
    "logsumexp", "std", "var", "median", "nanmedian", "quantile",
    "cumsum", "cumprod", "logcumsumexp", "diff",
    "all", "any", "isnan", "isinf", "isfinite", "isclose", "allclose",
    "equal_all", "equal", "not_equal", "greater_than", "greater_equal",
    "less_than", "less_equal", "logical_and", "logical_or", "logical_xor",
    "logical_not", "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
    "reshape", "transpose", "moveaxis", "unstack", "unbind", "split", "chunk",
    "squeeze", "unsqueeze", "flatten", "flip", "rot90", "roll", "tile",
    "expand", "expand_as", "broadcast_to", "gather", "gather_nd", "scatter",
    "scatter_nd_add", "index_select", "index_add", "index_sample",
    "take_along_axis", "put_along_axis", "masked_select", "masked_fill",
    "where", "unique", "unique_consecutive", "sort", "argsort", "topk",
    "kthvalue", "mode", "argmax", "argmin", "nonzero", "searchsorted",
    "bucketize", "repeat_interleave", "diagonal", "fill_diagonal",
    "tensordot", "as_complex", "as_real",
    "matmul", "bmm", "mm", "dot", "mv", "addmm", "norm", "dist", "cross",
    "cholesky", "inverse", "det", "slogdet", "svd", "qr", "eigvals",
    "pinv", "solve", "matrix_power", "matrix_rank", "lu", "lstsq",
    "cond", "histogram", "bincount", "trace", "cast", "zeros_like",
    "ones_like",
]
# to_sparse_coo / to_sparse_csr bind in paddle_tpu.sparse (they return
# SparseTensor, which this layer doesn't know about)


def _patch_tensor():
    for dunder, opname in _BINARY_DUNDERS.items():
        fn = _u(opname)

        def make(fn=fn):
            def meth(self, other):
                return fn(self, other)
            return meth
        setattr(Tensor, dunder, make())

    for dunder, opname in _REFLECTED.items():
        fn = _u(opname)

        def make_r(fn=fn):
            def meth(self, other):
                return fn(other, self)
            return meth
        setattr(Tensor, dunder, make_r())

    def _neg(self):
        return _u("neg")(self)

    def _abs(self):
        return _u("abs")(self)

    def _invert(self):
        return _u("logical_not")(self)

    Tensor.__neg__ = _neg
    Tensor.__abs__ = _abs
    Tensor.__invert__ = _invert

    seen = set()
    for name in _METHODS:
        if name in seen or name not in OPS:
            continue
        seen.add(name)
        fn = OPS[name].user_fn

        def make_m(fn=fn):
            def meth(self, *args, **kwargs):
                return fn(self, *args, **kwargs)
            return meth
        if not hasattr(Tensor, name):
            setattr(Tensor, name, make_m())


_patch_tensor()
