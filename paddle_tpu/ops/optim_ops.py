"""Optimizer update kernels (phi `sgd_`/`adam_`/... ops).

Reference: paddle/phi/kernels/*/{sgd,adam,adamw,momentum,rmsprop,lamb,
adagrad,adadelta,adamax}_kernel.* registered from
paddle/phi/api/yaml/legacy_ops.yaml.  The reference mutates params in place
on-device; here every kernel is a pure function returning the updated
state — the ``paddle_tpu.optimizer`` classes rebind tensor handles, and under
jit the whole update fuses into the train step (XLA fuses these elementwise
chains into a handful of kernels, which is the TPU-correct shape).

The trailing underscore names are kept for registry/coverage parity; the
user_fn still returns new Tensors (functional in-place).
"""

import jax
import jax.numpy as jnp

from .registry import op


@op("sgd_")
def sgd_(param, learning_rate, grad, master_param=None,
         multi_precision=False):
    lr = jnp.asarray(learning_rate, dtype=jnp.result_type(param, jnp.float32))
    if multi_precision and master_param is not None:
        new_master = master_param - lr * grad.astype(master_param.dtype)
        return new_master.astype(param.dtype), new_master
    return (param - (lr * grad).astype(param.dtype)), master_param


@op("momentum_")
def momentum_(param, grad, velocity, learning_rate, master_param=None,
              mu=0.9, use_nesterov=False, regularization_method="",
              regularization_coeff=0.0, multi_precision=False,
              rescale_grad=1.0):
    g = grad.astype(jnp.float32) * rescale_grad
    p = (master_param if multi_precision and master_param is not None
         else param).astype(jnp.float32)
    if regularization_method == "l2_decay":
        g = g + regularization_coeff * p
    v = mu * velocity + g
    lr = jnp.asarray(learning_rate, jnp.float32)
    if use_nesterov:
        p_new = p - lr * (g + mu * v)
    else:
        p_new = p - lr * v
    if multi_precision and master_param is not None:
        return p_new.astype(param.dtype), v, p_new
    return p_new.astype(param.dtype), v.astype(velocity.dtype), master_param


def _adam_core(p, g, m1, m2, b1p, b2p, lr, beta1, beta2, eps):
    m1n = beta1 * m1 + (1 - beta1) * g
    m2n = beta2 * m2 + (1 - beta2) * g * g
    denom = jnp.sqrt(m2n) / jnp.sqrt(1 - b2p) + eps
    p_new = p - lr * (m1n / (1 - b1p)) / denom
    return p_new, m1n, m2n


@op("adam_")
def adam_(param, grad, learning_rate, moment1, moment2, beta1_pow, beta2_pow,
          master_param=None, skip_update=None, beta1=0.9, beta2=0.999,
          epsilon=1e-8, lazy_mode=False, min_row_size_to_use_multithread=1000,
          multi_precision=False, use_global_beta_pow=False):
    f32 = jnp.float32
    p = (master_param if multi_precision and master_param is not None
         else param).astype(f32)
    g = grad.astype(f32)
    lr = jnp.asarray(learning_rate, f32)
    b1p_in = jnp.asarray(beta1_pow, f32)
    b2p_in = jnp.asarray(beta2_pow, f32)
    # reference adam_functors.h: bias correction uses the INPUT pows
    # (caller initializes them to beta); outputs advance them by one step
    p_new, m1n, m2n = _adam_core(p, g, moment1.astype(f32),
                                 moment2.astype(f32), b1p_in, b2p_in, lr,
                                 beta1, beta2, epsilon)
    b1p = b1p_in * beta1
    b2p = b2p_in * beta2
    if skip_update is not None:
        skip = jnp.asarray(skip_update).reshape(())
        p_new = jnp.where(skip, p, p_new)
        m1n = jnp.where(skip, moment1, m1n)
        m2n = jnp.where(skip, moment2, m2n)
        b1p = jnp.where(skip, beta1_pow, b1p)
        b2p = jnp.where(skip, beta2_pow, b2p)
    outs = (p_new.astype(param.dtype), m1n.astype(moment1.dtype),
            m2n.astype(moment2.dtype),
            b1p.astype(beta1_pow.dtype).reshape(jnp.shape(beta1_pow)),
            b2p.astype(beta2_pow.dtype).reshape(jnp.shape(beta2_pow)))
    if multi_precision and master_param is not None:
        return outs + (p_new,)
    return outs + (master_param,)


@op("adamw_")
def adamw_(param, grad, learning_rate, moment1, moment2, beta1_pow, beta2_pow,
           master_param=None, skip_update=None, beta1=0.9, beta2=0.999,
           epsilon=1e-8, lr_ratio=1.0, coeff=0.01, with_decay=True,
           lazy_mode=False, min_row_size_to_use_multithread=1000,
           multi_precision=False, use_global_beta_pow=False):
    f32 = jnp.float32
    p = (master_param if multi_precision and master_param is not None
         else param).astype(f32)
    g = grad.astype(f32)
    lr = jnp.asarray(learning_rate, f32) * lr_ratio
    if with_decay:
        p = p * (1.0 - lr * coeff)
    b1p_in = jnp.asarray(beta1_pow, f32)
    b2p_in = jnp.asarray(beta2_pow, f32)
    # reference adam_functors.h: bias correction uses the INPUT pows
    # (caller initializes them to beta); outputs advance them by one step
    p_new, m1n, m2n = _adam_core(p, g, moment1.astype(f32),
                                 moment2.astype(f32), b1p_in, b2p_in, lr,
                                 beta1, beta2, epsilon)
    b1p = b1p_in * beta1
    b2p = b2p_in * beta2
    if skip_update is not None:
        skip = jnp.asarray(skip_update).reshape(())
        p0 = (master_param if multi_precision and master_param is not None
              else param).astype(f32)
        p_new = jnp.where(skip, p0, p_new)
        m1n = jnp.where(skip, moment1, m1n)
        m2n = jnp.where(skip, moment2, m2n)
        b1p = jnp.where(skip, beta1_pow, b1p)
        b2p = jnp.where(skip, beta2_pow, b2p)
    outs = (p_new.astype(param.dtype), m1n.astype(moment1.dtype),
            m2n.astype(moment2.dtype),
            b1p.astype(beta1_pow.dtype).reshape(jnp.shape(beta1_pow)),
            b2p.astype(beta2_pow.dtype).reshape(jnp.shape(beta2_pow)))
    if multi_precision and master_param is not None:
        return outs + (p_new,)
    return outs + (master_param,)


@op("adamax_")
def adamax_(param, grad, learning_rate, moment, inf_norm, beta1_pow,
            master_param=None, beta1=0.9, beta2=0.999, epsilon=1e-8,
            multi_precision=False):
    f32 = jnp.float32
    p = param.astype(f32)
    g = grad.astype(f32)
    lr = jnp.asarray(learning_rate, f32)
    m = beta1 * moment.astype(f32) + (1 - beta1) * g
    u = jnp.maximum(beta2 * inf_norm.astype(f32), jnp.abs(g))
    p_new = p - lr / (1 - jnp.asarray(beta1_pow, f32)) * m / (u + epsilon)
    return (p_new.astype(param.dtype), m.astype(moment.dtype),
            u.astype(inf_norm.dtype), master_param)


@op("adagrad_")
def adagrad_(param, grad, moment, learning_rate, master_param=None,
             epsilon=1e-6, multi_precision=False):
    f32 = jnp.float32
    g = grad.astype(f32)
    mom = moment.astype(f32) + g * g
    lr = jnp.asarray(learning_rate, f32)
    p_new = param.astype(f32) - lr * g / (jnp.sqrt(mom) + epsilon)
    return (p_new.astype(param.dtype), mom.astype(moment.dtype), master_param)


@op("adadelta_")
def adadelta_(param, grad, avg_squared_grad, avg_squared_update,
              learning_rate=1.0, master_param=None, rho=0.95, epsilon=1e-6,
              multi_precision=False):
    f32 = jnp.float32
    g = grad.astype(f32)
    asg = rho * avg_squared_grad.astype(f32) + (1 - rho) * g * g
    update = -jnp.sqrt(avg_squared_update.astype(f32) + epsilon) / \
        jnp.sqrt(asg + epsilon) * g
    asu = rho * avg_squared_update.astype(f32) + (1 - rho) * update * update
    lr = jnp.asarray(learning_rate, f32)
    p_new = param.astype(f32) + lr * update
    return (p_new.astype(param.dtype), asg.astype(avg_squared_grad.dtype),
            asu.astype(avg_squared_update.dtype), master_param)


@op("rmsprop_")
def rmsprop_(param, mean_square, grad, moment, learning_rate,
             mean_grad=None, master_param=None, epsilon=1e-10, decay=0.9,
             momentum=0.0, centered=False, multi_precision=False):
    f32 = jnp.float32
    g = grad.astype(f32)
    ms = decay * mean_square.astype(f32) + (1 - decay) * g * g
    lr = jnp.asarray(learning_rate, f32)
    if centered and mean_grad is not None:
        mg = decay * mean_grad.astype(f32) + (1 - decay) * g
        denom = jnp.sqrt(ms - mg * mg + epsilon)
    else:
        mg = mean_grad
        denom = jnp.sqrt(ms + epsilon)
    mom = momentum * moment.astype(f32) + lr * g / denom
    p_new = param.astype(f32) - mom
    return (p_new.astype(param.dtype), mom.astype(moment.dtype),
            ms.astype(mean_square.dtype),
            mg if mg is None or not centered else mg.astype(mean_grad.dtype),
            master_param)


@op("lamb_")
def lamb_(param, grad, learning_rate, moment1, moment2, beta1_pow, beta2_pow,
          master_param=None, skip_update=None, weight_decay=0.01, beta1=0.9,
          beta2=0.999, epsilon=1e-6, always_adapt=False,
          multi_precision=False):
    f32 = jnp.float32
    p = (master_param if multi_precision and master_param is not None
         else param).astype(f32)
    g = grad.astype(f32)
    lr = jnp.asarray(learning_rate, f32)
    m1n = beta1 * moment1.astype(f32) + (1 - beta1) * g
    m2n = beta2 * moment2.astype(f32) + (1 - beta2) * g * g
    b1p_in = jnp.asarray(beta1_pow, f32)
    b2p_in = jnp.asarray(beta2_pow, f32)
    m_hat = m1n / (1 - b1p_in)
    v_hat = m2n / (1 - b2p_in)
    b1p = b1p_in * beta1
    b2p = b2p_in * beta2
    r = m_hat / (jnp.sqrt(v_hat) + epsilon) + weight_decay * p
    w_norm = jnp.linalg.norm(p)
    r_norm = jnp.linalg.norm(r)
    trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
    p_new = p - lr * trust * r
    outs = (p_new.astype(param.dtype), m1n.astype(moment1.dtype),
            m2n.astype(moment2.dtype),
            b1p.astype(beta1_pow.dtype).reshape(jnp.shape(beta1_pow)),
            b2p.astype(beta2_pow.dtype).reshape(jnp.shape(beta2_pow)))
    if multi_precision and master_param is not None:
        return outs + (p_new,)
    return outs + (master_param,)


# ---- merged / fused list variants (phi merged_adam_/merged_momentum_/
#      fused_adam_: one kernel over many params; under XLA each update
#      fuses anyway, so these are loops over the scalar kernels) ----

def _listify(x, n):
    if x is None:
        return [None] * n
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x] * n


@op("merged_adam_")
def merged_adam_(params, grads, learning_rate, moments1, moments2,
                 beta1_pows, beta2_pows, master_params=None, beta1=0.9,
                 beta2=0.999, epsilon=1e-8, multi_precision=False,
                 use_global_beta_pow=False):
    n = len(params)
    lrs = _listify(learning_rate, n)
    mps = _listify(master_params, n)
    outs = ([], [], [], [], [], [])
    for i in range(n):
        r = adam_.__wrapped__(params[i], grads[i], lrs[i], moments1[i],
                              moments2[i], beta1_pows[i], beta2_pows[i],
                              master_param=mps[i], beta1=beta1, beta2=beta2,
                              epsilon=epsilon,
                              multi_precision=multi_precision)
        for j in range(6):
            outs[j].append(r[j])
    return outs


@op("merged_momentum_")
def merged_momentum_(params, grads, velocitys, learning_rate,
                     master_params=None, mu=0.9, use_nesterov=False,
                     regularization_method=None, regularization_coeff=None,
                     multi_precision=False, rescale_grad=1.0):
    n = len(params)
    lrs = _listify(learning_rate, n)
    mps = _listify(master_params, n)
    rms = regularization_method or [""] * n
    rcs = regularization_coeff or [0.0] * n
    outs = ([], [], [])
    for i in range(n):
        r = momentum_.__wrapped__(
            params[i], grads[i], velocitys[i], lrs[i], master_param=mps[i],
            mu=mu, use_nesterov=use_nesterov,
            regularization_method=rms[i] if i < len(rms) else "",
            regularization_coeff=rcs[i] if i < len(rcs) else 0.0,
            multi_precision=multi_precision, rescale_grad=rescale_grad)
        for j in range(3):
            outs[j].append(r[j])
    return outs


@op("fused_adam_")
def fused_adam_(params, grads, learning_rate, moments1, moments2,
                beta1_pows, beta2_pows, master_params=None,
                skip_update=None, beta1=0.9, beta2=0.999, epsilon=1e-8,
                chunk_size=32768, weight_decay=0.0, use_adamw=False,
                multi_precision=False, use_global_beta_pow=False):
    n = len(params)
    mps = _listify(master_params, n)
    outs = ([], [], [], [], [], [])
    for i in range(n):
        if use_adamw:
            r = adamw_.__wrapped__(
                params[i], grads[i], learning_rate, moments1[i], moments2[i],
                beta1_pows[i], beta2_pows[i], master_param=mps[i],
                skip_update=skip_update, beta1=beta1, beta2=beta2,
                epsilon=epsilon, coeff=weight_decay,
                with_decay=weight_decay > 0.0,
                multi_precision=multi_precision)
        else:
            r = adam_.__wrapped__(
                params[i], grads[i], learning_rate, moments1[i], moments2[i],
                beta1_pows[i], beta2_pows[i], master_param=mps[i],
                skip_update=skip_update, beta1=beta1, beta2=beta2,
                epsilon=epsilon, multi_precision=multi_precision)
        for j in range(6):
            outs[j].append(r[j])
    return outs


# ---- AMP loss-scaling kernels (phi update_loss_scaling_/
#      check_finite_and_unscale_; reference GPU impls at
#      paddle/phi/kernels/gpu/amp_kernel.cu) ----

@op("check_finite_and_unscale_")
def check_finite_and_unscale_(xs, scale):
    inv = 1.0 / jnp.asarray(scale, jnp.float32)
    found_inf = jnp.zeros((), jnp.bool_)
    outs = []
    for x in xs:
        xf = x.astype(jnp.float32) * inv
        found_inf = found_inf | ~jnp.isfinite(xf).all()
        outs.append(xf.astype(x.dtype))
    return outs, found_inf.reshape((1,))


@op("update_loss_scaling_")
def update_loss_scaling_(xs, found_infinite, prev_loss_scaling,
                         in_good_steps, in_bad_steps, incr_every_n_steps=2000,
                         decr_every_n_nan_or_inf=2, incr_ratio=2.0,
                         decr_ratio=0.5, stop_update=False):
    found = jnp.asarray(found_infinite).reshape(()).astype(jnp.bool_)
    good = jnp.asarray(in_good_steps).reshape(()).astype(jnp.int32)
    bad = jnp.asarray(in_bad_steps).reshape(()).astype(jnp.int32)
    scale = jnp.asarray(prev_loss_scaling, jnp.float32).reshape(())

    bad_new = jnp.where(found, bad + 1, 0)
    good_new = jnp.where(found, 0, good + 1)
    shrink = bad_new >= decr_every_n_nan_or_inf
    grow = good_new >= incr_every_n_steps
    scale_new = jnp.where(shrink, jnp.maximum(scale * decr_ratio, 1.0), scale)
    scale_new = jnp.where(grow, scale * incr_ratio, scale_new)
    bad_new = jnp.where(shrink, 0, bad_new)
    good_new = jnp.where(grow, 0, good_new)
    # the reference feeds StopUpdate as a device tensor: select on
    # device instead of a python branch (`if tensor:` would sync the
    # value to host in eager and fail outright under jit)
    if isinstance(stop_update, (bool, int)):
        if stop_update:
            scale_new, good_new, bad_new = scale, good, bad
    else:
        stop = jnp.asarray(stop_update).reshape(()).astype(jnp.bool_)
        scale_new = jnp.where(stop, scale, scale_new)
        good_new = jnp.where(stop, good, good_new)
        bad_new = jnp.where(stop, bad, bad_new)
    outs = [jnp.where(found, jnp.zeros_like(x), x) for x in xs]
    return (outs, scale_new.reshape((1,)), good_new.reshape((1,)),
            bad_new.reshape((1,)))


@op("average_accumulates_")
def average_accumulates_(param, in_sum_1, in_sum_2, in_sum_3,
                         in_num_accumulates, in_old_num_accumulates,
                         in_num_updates, average_window=10000,
                         max_average_window=10000, min_average_window=10000):
    num_acc = jnp.asarray(in_num_accumulates).reshape(()) + 1
    num_upd = jnp.asarray(in_num_updates).reshape(()) + 1
    old_num = jnp.asarray(in_old_num_accumulates).reshape(())
    s1 = in_sum_1 + param
    s2, s3 = in_sum_2, in_sum_3
    window = jnp.minimum(
        jnp.maximum(num_upd * average_window, min_average_window),
        max_average_window).astype(num_acc.dtype)
    roll = num_acc + old_num >= window
    s2_new = jnp.where(roll, s1 + s2, s2)
    s1_new = jnp.where(roll, jnp.zeros_like(s1), s1)
    s3_new = jnp.where(roll, jnp.zeros_like(s3), s3)
    old_new = jnp.where(roll, num_acc + old_num, old_num)
    num_new = jnp.where(roll, jnp.zeros_like(num_acc), num_acc)
    return (s1_new, s2_new, s3_new, num_new.reshape((1,)),
            old_new.reshape((1,)), num_upd.reshape((1,)))
