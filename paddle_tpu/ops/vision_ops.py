"""Vision ops: interpolation, sampling, ROI pooling, detection post-processing.

Reference kernels: paddle/phi/kernels/*/{interpolate,grid_sample,affine_grid,
roi_align,roi_pool,psroi_pool,nms,yolo_box,yolo_loss,prior_box,box_coder,
deformable_conv,...}_kernel.* and legacy detection ops under
paddle/fluid/operators/detection/.

TPU design notes: everything here is expressed as gathers + elementwise math
(static shapes), which XLA vectorizes well.  Detection post-processing ops
(NMS family) that are inherently dynamic-shape in the reference return
fixed-capacity padded outputs plus a valid-count — the standard TPU idiom —
while the eager wrappers trim to the dynamic size on host when possible.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .registry import op

# ---------------------------------------------------------------- interpolate

def _cround(x):
    """C round(): half-away-from-zero — jnp.round is half-to-even, which
    diverges from the phi roi kernels at half-integer box coordinates."""
    return jnp.sign(x) * jnp.floor(jnp.abs(x) + 0.5)


def _axis_coords(out_size, in_size, align_corners, align_mode=1):
    """Source coordinates for each output index along one axis (float32)."""
    o = jnp.arange(out_size, dtype=jnp.float32)
    if align_corners and out_size > 1:
        return o * ((in_size - 1) / (out_size - 1))
    scale = in_size / out_size
    if align_mode == 1:  # paddle align_mode=1: src = dst * scale
        return o * scale
    return jnp.clip((o + 0.5) * scale - 0.5, 0.0, in_size - 1)


def _interp_linear_axis(x, axis, out_size, align_corners, align_mode=1):
    in_size = x.shape[axis]
    c = _axis_coords(out_size, in_size, align_corners, align_mode)
    lo = jnp.clip(jnp.floor(c).astype(jnp.int32), 0, in_size - 1)
    hi = jnp.clip(lo + 1, 0, in_size - 1)
    w = (c - lo.astype(jnp.float32))
    xl = jnp.take(x, lo, axis=axis)
    xh = jnp.take(x, hi, axis=axis)
    bshape = [1] * x.ndim
    bshape[axis] = out_size
    w = w.reshape(bshape)
    return (xl.astype(jnp.float32) * (1 - w) + xh.astype(jnp.float32) * w)


def _cubic_w(t, a=-0.75):
    t = jnp.abs(t)
    w1 = ((a + 2) * t - (a + 3)) * t * t + 1
    w2 = (((t - 5) * t + 8) * t - 4) * a
    return jnp.where(t <= 1, w1, jnp.where(t < 2, w2, 0.0))


def _interp_cubic_axis(x, axis, out_size, align_corners):
    in_size = x.shape[axis]
    c = _axis_coords(out_size, in_size, align_corners, align_mode=0)
    base = jnp.floor(c).astype(jnp.int32)
    frac = c - base.astype(jnp.float32)
    out = 0.0
    for k in range(-1, 3):
        idx = jnp.clip(base + k, 0, in_size - 1)
        w = _cubic_w(frac - k)
        bshape = [1] * x.ndim
        bshape[axis] = out_size
        out = out + jnp.take(x, idx, axis=axis).astype(jnp.float32) * \
            w.reshape(bshape)
    return out


def _interp_nearest_axis(x, axis, out_size, align_corners):
    in_size = x.shape[axis]
    c = _axis_coords(out_size, in_size, align_corners, align_mode=1)
    idx = (jnp.round(c) if align_corners else jnp.floor(c)).astype(jnp.int32)
    return jnp.take(x, jnp.clip(idx, 0, in_size - 1), axis=axis)


def _spatial_axes(ndim, data_format):
    if data_format.startswith("NC"):
        return list(range(2, ndim))
    return list(range(1, ndim - 1))


def _resolve_sizes(x, axes, size, scale_factor):
    if size is not None:
        sizes = [int(s) for s in (size if isinstance(size, (list, tuple))
                                  else [size] * len(axes))]
    else:
        sf = (scale_factor if isinstance(scale_factor, (list, tuple))
              else [scale_factor] * len(axes))
        sizes = [int(x.shape[a] * float(f)) for a, f in zip(axes, sf)]
    return sizes


def _interp_impl(x, mode, size, scale_factor, align_corners, align_mode,
                 data_format):
    axes = _spatial_axes(x.ndim, data_format)
    sizes = _resolve_sizes(x, axes, size, scale_factor)
    out = x
    for a, s in zip(axes, sizes):
        if mode == "nearest":
            out = _interp_nearest_axis(out, a, s, align_corners)
        elif mode in ("linear", "bilinear", "trilinear"):
            out = _interp_linear_axis(out, a, s, align_corners, align_mode)
        elif mode == "bicubic":
            out = _interp_cubic_axis(out, a, s, align_corners)
        elif mode == "area":
            out = jax.image.resize(
                out, tuple(s if i == a else d
                           for i, d in enumerate(out.shape)), "linear")
        else:
            raise ValueError(f"unknown interpolate mode {mode}")
    return out.astype(x.dtype) if mode == "nearest" else out


def _make_interp(mode):
    def fn(x, out_size=None, size_tensor=None, scale_tensor=None, scale=None,
           data_format="NCHW", align_corners=True, align_mode=1,
           size=None, scale_factor=None):
        size = size if size is not None else out_size
        scale_factor = scale_factor if scale_factor is not None else scale
        return _interp_impl(x, mode.replace("_interp", ""), size,
                            scale_factor, align_corners, align_mode,
                            data_format)
    fn.__name__ = mode
    return fn


linear_interp = op("linear_interp")(_make_interp("linear_interp"))
bilinear_interp = op("bilinear_interp")(_make_interp("bilinear_interp"))
trilinear_interp = op("trilinear_interp")(_make_interp("trilinear_interp"))
nearest_interp = op("nearest_interp")(_make_interp("nearest_interp"))
bicubic_interp = op("bicubic_interp")(_make_interp("bicubic_interp"))


# ------------------------------------------------------- affine / grid sample

@op()
def affine_grid(theta, out_shape, align_corners=True):
    """theta [N, 2, 3] (or [N, 3, 4] for 3d), out_shape (N, C, H, W)."""
    out_shape = [int(s) for s in np.asarray(out_shape).reshape(-1)]  # noqa: H001 (shape attr)
    is_3d = theta.shape[-2] == 3
    if not is_3d:
        n, _, h, w = out_shape
        ys = jnp.linspace(-1, 1, h) if align_corners else \
            (jnp.arange(h) * 2 + 1) / h - 1
        xs = jnp.linspace(-1, 1, w) if align_corners else \
            (jnp.arange(w) * 2 + 1) / w - 1
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        base = jnp.stack([gx, gy, jnp.ones_like(gx)], -1)  # [H,W,3]
        grid = jnp.einsum("hwk,njk->nhwj", base.astype(theta.dtype), theta)
        return grid  # [N,H,W,2]
    n, _, d, h, w = out_shape
    lin = (lambda s: jnp.linspace(-1, 1, s)) if align_corners else \
        (lambda s: (jnp.arange(s) * 2 + 1) / s - 1)
    gz, gy, gx = jnp.meshgrid(lin(d), lin(h), lin(w), indexing="ij")
    base = jnp.stack([gx, gy, gz, jnp.ones_like(gx)], -1)
    return jnp.einsum("dhwk,njk->ndhwj", base.astype(theta.dtype), theta)


def _grid_sample_2d(x, grid, mode, padding_mode, align_corners):
    n, c, h, w = x.shape
    gx, gy = grid[..., 0], grid[..., 1]

    def unnorm(g, size):
        if align_corners:
            return (g + 1) * (size - 1) / 2
        return ((g + 1) * size - 1) / 2

    ix = unnorm(gx.astype(jnp.float32), w)
    iy = unnorm(gy.astype(jnp.float32), h)

    if padding_mode == "border":
        ix = jnp.clip(ix, 0, w - 1)
        iy = jnp.clip(iy, 0, h - 1)
    elif padding_mode == "reflection":
        def reflect(v, size):
            if align_corners:
                span = 2 * (size - 1)
                v = jnp.abs(v) % jnp.maximum(span, 1)
                return jnp.where(v > size - 1, span - v, v)
            span = 2 * size
            v = (v + 0.5) % span
            v = jnp.where(v < 0, v + span, v)
            v = jnp.where(v >= size, span - v, v) - 0.5
            return jnp.clip(v, 0, size - 1)
        ix = reflect(ix, w)
        iy = reflect(iy, h)

    def sample(iy_i, ix_i):
        valid = ((ix_i >= 0) & (ix_i <= w - 1) & (iy_i >= 0)
                 & (iy_i <= h - 1))
        xi = jnp.clip(ix_i, 0, w - 1).astype(jnp.int32)
        yi = jnp.clip(iy_i, 0, h - 1).astype(jnp.int32)
        # x: [N,C,H,W]; yi/xi: [N,Ho,Wo]
        g = jax.vmap(lambda img, yy, xx: img[:, yy, xx])(x, yi, xi)
        return jnp.where(valid[:, None], g.reshape(n, c, -1)
                         .reshape(n, c, *yi.shape[1:]), 0.0) \
            if padding_mode == "zeros" else g

    if mode == "nearest":
        return sample(jnp.round(iy), jnp.round(ix)).astype(x.dtype)

    x0, y0 = jnp.floor(ix), jnp.floor(iy)
    x1, y1 = x0 + 1, y0 + 1
    wa = ((x1 - ix) * (y1 - iy))[:, None]
    wb = ((x1 - ix) * (iy - y0))[:, None]
    wc = ((ix - x0) * (y1 - iy))[:, None]
    wd = ((ix - x0) * (iy - y0))[:, None]
    va = sample(y0, x0).astype(jnp.float32)
    vb = sample(y1, x0).astype(jnp.float32)
    vc = sample(y0, x1).astype(jnp.float32)
    vd = sample(y1, x1).astype(jnp.float32)
    return (va * wa + vb * wb + vc * wc + vd * wd).astype(x.dtype)


@op()
def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True):
    return _grid_sample_2d(x, grid, mode, padding_mode, align_corners)


# ------------------------------------------------------------------ ROI ops

def _roi_bilinear(feat, y, x):
    """feat [C,H,W]; y/x arbitrary same-shape float coords → [C, *coords]."""
    c, h, w = feat.shape
    y0 = jnp.clip(jnp.floor(y), 0, h - 1)
    x0 = jnp.clip(jnp.floor(x), 0, w - 1)
    y1 = jnp.clip(y0 + 1, 0, h - 1)
    x1 = jnp.clip(x0 + 1, 0, w - 1)
    ly, lx = y - y0, x - x0
    y0i, y1i = y0.astype(jnp.int32), y1.astype(jnp.int32)
    x0i, x1i = x0.astype(jnp.int32), x1.astype(jnp.int32)
    v00 = feat[:, y0i, x0i]
    v01 = feat[:, y0i, x1i]
    v10 = feat[:, y1i, x0i]
    v11 = feat[:, y1i, x1i]
    return (v00 * (1 - ly) * (1 - lx) + v01 * (1 - ly) * lx
            + v10 * ly * (1 - lx) + v11 * ly * lx)


@op()
def roi_align(x, boxes, boxes_num=None, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, aligned=True):
    """x [N,C,H,W]; boxes [R,4] (x1,y1,x2,y2); boxes_num [N] rois per image."""
    n, c, h, w = x.shape
    r = boxes.shape[0]
    if boxes_num is None:
        batch_idx = jnp.zeros((r,), jnp.int32)
    else:
        bn = jnp.asarray(boxes_num, jnp.int32)
        batch_idx = jnp.sum(
            jnp.arange(r)[:, None] >= jnp.cumsum(bn)[None, :], axis=1
        ).astype(jnp.int32)
    offset = 0.5 if aligned else 0.0
    sr = sampling_ratio if sampling_ratio > 0 else 2
    bx = boxes.astype(jnp.float32) * spatial_scale - offset

    def one_roi(box, bidx):
        x1, y1, x2, y2 = box
        rw = jnp.maximum(x2 - x1, 1e-3 if aligned else 1.0)
        rh = jnp.maximum(y2 - y1, 1e-3 if aligned else 1.0)
        bin_h = rh / pooled_height
        bin_w = rw / pooled_width
        py = jnp.arange(pooled_height, dtype=jnp.float32)
        px = jnp.arange(pooled_width, dtype=jnp.float32)
        sy = jnp.arange(sr, dtype=jnp.float32)
        yy = y1 + (py[:, None] + (sy[None, :] + 0.5) / sr) * bin_h
        xx = x1 + (px[:, None] + (sy[None, :] + 0.5) / sr) * bin_w
        gy = jnp.clip(yy, 0, h - 1).reshape(-1)  # [PH*sr]
        gx = jnp.clip(xx, 0, w - 1).reshape(-1)  # [PW*sr]
        gyy = jnp.repeat(gy, gx.shape[0])
        gxx = jnp.tile(gx, gy.shape[0])
        feat = x[bidx].astype(jnp.float32)
        vals = _roi_bilinear(feat, gyy, gxx)  # [C, PH*sr*PW*sr]
        vals = vals.reshape(c, pooled_height, sr, pooled_width, sr)
        return vals.mean(axis=(2, 4))

    return jax.vmap(one_roi)(bx, batch_idx).astype(x.dtype)


@op()
def roi_pool(x, boxes, boxes_num=None, pooled_height=1, pooled_width=1,
             spatial_scale=1.0):
    n, c, h, w = x.shape
    r = boxes.shape[0]
    if boxes_num is None:
        batch_idx = jnp.zeros((r,), jnp.int32)
    else:
        bn = jnp.asarray(boxes_num, jnp.int32)
        batch_idx = jnp.sum(
            jnp.arange(r)[:, None] >= jnp.cumsum(bn)[None, :], axis=1
        ).astype(jnp.int32)
    bx = _cround(boxes.astype(jnp.float32) * spatial_scale)

    def one_roi(box, bidx):
        x1, y1, x2, y2 = box
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        bin_h, bin_w = rh / pooled_height, rw / pooled_width
        ys = jnp.arange(h, dtype=jnp.float32)
        xs = jnp.arange(w, dtype=jnp.float32)
        feat = x[bidx]
        # reference phi roi_pool bins OVERLAP: bin i spans
        # [floor(i*bin), ceil((i+1)*bin)) — a pixel on a fractional
        # boundary feeds BOTH neighbors (caught by the round-3 exact
        # formula check; the old disjoint floor-assignment differed on
        # rois whose size doesn't divide the pooled grid)
        ph_idx = jnp.arange(pooled_height, dtype=jnp.float32)
        pw_idx = jnp.arange(pooled_width, dtype=jnp.float32)
        y_start = jnp.floor(ph_idx * bin_h)
        y_end = jnp.ceil((ph_idx + 1) * bin_h)
        x_start = jnp.floor(pw_idx * bin_w)
        x_end = jnp.ceil((pw_idx + 1) * bin_w)
        ry = ys[:, None] - y1                               # [H, 1]
        rx = xs[:, None] - x1                               # [W, 1]
        ymask = (ry >= y_start[None, :]) & (ry < y_end[None, :]) & \
            (ry >= 0) & (ry < rh)                           # [H, PH]
        xmask = (rx >= x_start[None, :]) & (rx < x_end[None, :]) & \
            (rx >= 0) & (rx < rw)                           # [W, PW]
        big = feat[:, :, :, None, None].astype(jnp.float32)  # [C,H,W,1,1]
        m = ymask[None, :, None, :, None] & xmask[None, None, :, None, :]
        masked = jnp.where(m, big, -jnp.inf)
        out = masked.max(axis=(1, 2))
        return jnp.where(jnp.isfinite(out), out, 0.0)

    return jax.vmap(one_roi)(bx, batch_idx).astype(x.dtype)


@op()
def psroi_pool(x, boxes, boxes_num=None, pooled_height=1, pooled_width=1,
               output_channels=1, spatial_scale=1.0):
    n, c, h, w = x.shape
    r = boxes.shape[0]
    if boxes_num is None:
        batch_idx = jnp.zeros((r,), jnp.int32)
    else:
        bn = jnp.asarray(boxes_num, jnp.int32)
        batch_idx = jnp.sum(
            jnp.arange(r)[:, None] >= jnp.cumsum(bn)[None, :], axis=1
        ).astype(jnp.int32)
    bx = boxes.astype(jnp.float32)

    def one_roi(box, bidx):
        # reference phi psroi_pool (psroi_pool_kernel.cc): roi endpoints
        # are round(x1)*scale .. (round(x2)+1)*scale (rounding the RAW
        # box coordinate, unlike roi_pool which rounds box*scale); each bin AVERAGES
        # the integer-pixel window [floor(ph*bin+y1), ceil((ph+1)*bin+y1))
        # (empty bins zero), and the position-sensitive input channel is
        # (oc*PH + ph)*PW + pw — oc-major.  (The old bilinear
        # sub-sampling + transposed channel layout were divergences
        # caught by the round-3 exact-reference pass.)
        bx1, by1, bx2, by2 = box
        x1 = _cround(bx1) * spatial_scale
        y1 = _cround(by1) * spatial_scale
        x2 = (_cround(bx2) + 1.0) * spatial_scale
        y2 = (_cround(by2) + 1.0) * spatial_scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_h, bin_w = rh / pooled_height, rw / pooled_width
        # oc-major position-sensitive layout: [OC, PH, PW, H, W]
        feat = x[bidx].astype(jnp.float32).reshape(
            output_channels, pooled_height, pooled_width, h, w)
        ph_idx = jnp.arange(pooled_height, dtype=jnp.float32)
        pw_idx = jnp.arange(pooled_width, dtype=jnp.float32)
        ys = jnp.arange(h, dtype=jnp.float32)[:, None]     # [H, 1]
        xs = jnp.arange(w, dtype=jnp.float32)[:, None]     # [W, 1]
        y_lo = jnp.clip(jnp.floor(ph_idx * bin_h + y1), 0, h)
        y_hi = jnp.clip(jnp.ceil((ph_idx + 1) * bin_h + y1), 0, h)
        x_lo = jnp.clip(jnp.floor(pw_idx * bin_w + x1), 0, w)
        x_hi = jnp.clip(jnp.ceil((pw_idx + 1) * bin_w + x1), 0, w)
        ymask = ((ys >= y_lo[None, :]) &
                 (ys < y_hi[None, :])).astype(jnp.float32)  # [H, PH]
        xmask = ((xs >= x_lo[None, :]) &
                 (xs < x_hi[None, :])).astype(jnp.float32)  # [W, PW]
        # contract each bin only with ITS OWN channel slice (PH*PW-fold
        # less work than averaging every channel at every bin)
        sums = jnp.einsum("oPQhw,hP,wQ->oPQ", feat, ymask, xmask)
        counts = jnp.einsum("hP,wQ->PQ", ymask, xmask)
        vals = sums / jnp.maximum(counts, 1.0)[None]
        return jnp.where(counts[None] > 0, vals, 0.0)  # [OC, PH, PW]

    return jax.vmap(one_roi)(bx, batch_idx).astype(x.dtype)


# -------------------------------------------------------------- NMS family

def _iou_matrix(boxes):
    x1, y1, x2, y2 = (boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3])
    area = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
    ix1 = jnp.maximum(x1[:, None], x1[None, :])
    iy1 = jnp.maximum(y1[:, None], y1[None, :])
    ix2 = jnp.minimum(x2[:, None], x2[None, :])
    iy2 = jnp.minimum(y2[:, None], y2[None, :])
    inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
    union = area[:, None] + area[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _nms_mask(boxes, scores, iou_threshold):
    """Greedy NMS as a fixed-trip loop → keep mask (jit-friendly)."""
    n = boxes.shape[0]
    order = jnp.argsort(-scores)
    iou = _iou_matrix(boxes)[order][:, order]
    keep = jnp.ones((n,), jnp.bool_)

    def body(i, keep):
        sup = (iou[i] > iou_threshold) & keep[i] & \
            (jnp.arange(n) > i)
        return keep & ~sup

    keep = jax.lax.fori_loop(0, n, body, keep)
    inv = jnp.zeros((n,), jnp.int32).at[order].set(jnp.arange(n))
    return keep[inv]


@op()
def nms(boxes, iou_threshold=0.3, scores=None):
    if scores is None:
        scores = -jnp.arange(boxes.shape[0], dtype=jnp.float32)
    scores = jnp.asarray(scores, jnp.float32)
    keep = _nms_mask(boxes.astype(jnp.float32), scores, iou_threshold)
    # kept indices first (score-ordered), suppressed after; count = #kept
    order = jnp.argsort(-jnp.where(keep, scores, -jnp.inf))
    return order, keep.sum()


@op()
def matrix_nms(bboxes, scores, score_threshold=0.0, post_threshold=0.0,
               nms_top_k=-1, keep_top_k=-1, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True):
    """Matrix NMS (SOLOv2) — decayed scores, fully parallel.

    bboxes [N, M, 4], scores [N, C, M].  Returns (out [N*K, 6], index,
    rois_num) with K = keep_top_k capacity, padded with -1 scores.
    """
    n, cnum, m = scores.shape
    k = keep_top_k if keep_top_k > 0 else m

    def per_image(bb, sc):
        top = nms_top_k if 0 < nms_top_k < m else m
        all_scores, all_cls, all_box = [], [], []
        for ci in range(cnum):
            if ci == background_label:
                continue
            s = sc[ci]
            ord_ = jnp.argsort(-s)[:top]
            s_s = s[ord_]
            b_s = bb[ord_]
            iou = _iou_matrix(b_s)
            iou = jnp.triu(iou, k=1)  # iou[i, j], i higher-scored than j
            # max_iou[i] = max IoU of box i with any higher-scored box —
            # the decay of j is compensated by how suppressed i itself is
            max_iou = jnp.max(iou, axis=0)
            upper = jnp.triu(jnp.ones_like(iou), 1) > 0
            if use_gaussian:
                decay = jnp.exp(-(iou ** 2 - max_iou[:, None] ** 2)
                                / gaussian_sigma)
                decay = jnp.min(jnp.where(upper, decay, 1.0), axis=0)
            else:
                decay = jnp.min(jnp.where(
                    upper,
                    (1 - iou) / jnp.maximum(1 - max_iou[:, None], 1e-9),
                    1.0), axis=0)
            s_d = s_s * decay
            s_d = jnp.where(s_s > score_threshold, s_d, -1.0)
            s_d = jnp.where(s_d > post_threshold, s_d, -1.0)
            all_scores.append(s_d)
            all_cls.append(jnp.full_like(s_d, ci))
            all_box.append(b_s)
        s_all = jnp.concatenate(all_scores)
        c_all = jnp.concatenate(all_cls)
        b_all = jnp.concatenate(all_box, axis=0)
        ord_ = jnp.argsort(-s_all)[:k]
        s_k, c_k, b_k = s_all[ord_], c_all[ord_], b_all[ord_]
        out = jnp.concatenate([c_k[:, None], s_k[:, None], b_k], axis=1)
        cnt = (s_k > 0).sum()
        return out, cnt

    outs, cnts = jax.vmap(per_image)(bboxes.astype(jnp.float32),
                                     scores.astype(jnp.float32))
    return outs.reshape(-1, 6), jnp.zeros((n * k,), jnp.int32), \
        cnts.astype(jnp.int32)


@op()
def multiclass_nms3(bboxes, scores, rois_num=None, score_threshold=0.05,
                    nms_top_k=-1, keep_top_k=100, nms_threshold=0.3,
                    normalized=True, nms_eta=1.0, background_label=-1):
    """bboxes [N, M, 4], scores [N, C, M] → padded [N*K, 6] + counts."""
    n, cnum, m = scores.shape
    k = keep_top_k if keep_top_k > 0 else m

    def per_image(bb, sc):
        all_s, all_c, all_b = [], [], []
        for ci in range(cnum):
            if ci == background_label:
                continue
            s = sc[ci]
            keep = _nms_mask(bb, s, nms_threshold)
            s = jnp.where(keep & (s >= score_threshold), s, -1.0)
            all_s.append(s)
            all_c.append(jnp.full_like(s, ci))
            all_b.append(bb)
        s_all = jnp.concatenate(all_s)
        c_all = jnp.concatenate(all_c)
        b_all = jnp.concatenate(all_b, axis=0)
        ord_ = jnp.argsort(-s_all)[:k]
        s_k, c_k, b_k = s_all[ord_], c_all[ord_], b_all[ord_]
        out = jnp.concatenate([c_k[:, None], s_k[:, None], b_k], axis=1)
        return out, (s_k > 0).sum()

    outs, cnts = jax.vmap(per_image)(bboxes.astype(jnp.float32),
                                     scores.astype(jnp.float32))
    return outs.reshape(-1, 6), jnp.zeros((n * k,), jnp.int32), \
        cnts.astype(jnp.int32)


# ----------------------------------------------------------- box utilities

@op()
def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, axis=0,
              variance=None):
    pb = prior_box.astype(jnp.float32)
    tb = target_box.astype(jnp.float32)
    norm = 0.0 if box_normalized else 1.0
    pw = pb[:, 2] - pb[:, 0] + norm
    ph = pb[:, 3] - pb[:, 1] + norm
    pcx = pb[:, 0] + pw * 0.5
    pcy = pb[:, 1] + ph * 0.5
    if prior_box_var is not None:
        var = prior_box_var.astype(jnp.float32)
    elif variance:
        var = jnp.asarray(variance, jnp.float32)[None, :]
    else:
        var = jnp.ones((1, 4), jnp.float32)
    if code_type == "encode_center_size":
        tw = tb[:, 2] - tb[:, 0] + norm
        th = tb[:, 3] - tb[:, 1] + norm
        tcx = tb[:, 0] + tw * 0.5
        tcy = tb[:, 1] + th * 0.5
        ox = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        oy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        ow = jnp.log(tw[:, None] / pw[None, :])
        oh = jnp.log(th[:, None] / ph[None, :])
        out = jnp.stack([ox, oy, ow, oh], axis=-1)
        return out / var[None, :, :] if var.ndim == 2 else out / var
    # decode_center_size: target [R, ..., 4]
    if tb.ndim == 2:
        tb = tb[:, None, :]
    if axis == 0:
        pcx_, pcy_, pw_, ph_ = (pcx[None, :], pcy[None, :],
                                pw[None, :], ph[None, :])
    else:
        pcx_, pcy_, pw_, ph_ = (pcx[:, None], pcy[:, None],
                                pw[:, None], ph[:, None])
    v = var if var.ndim == 2 else var
    t = tb * (v[None, :, :] if v.shape[0] != tb.shape[0] else v[:, None, :]) \
        if v.size > 4 else tb * v.reshape(1, 1, 4)
    dcx = t[..., 0] * pw_ + pcx_
    dcy = t[..., 1] * ph_ + pcy_
    dw = jnp.exp(t[..., 2]) * pw_
    dh = jnp.exp(t[..., 3]) * ph_
    return jnp.stack([dcx - dw * 0.5, dcy - dh * 0.5,
                      dcx + dw * 0.5 - norm, dcy + dh * 0.5 - norm], axis=-1)


@op()
def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variances=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False):
    fh, fw = input.shape[-2], input.shape[-1]
    ih, iw = image.shape[-2], image.shape[-1]
    step_w = steps[0] if steps[0] > 0 else iw / fw
    step_h = steps[1] if steps[1] > 0 else ih / fh
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)
    boxes = []
    for ms in min_sizes:
        boxes.append((ms, ms))
        if max_sizes:
            for mx in max_sizes:
                s = float(np.sqrt(ms * mx))  # noqa: H001 (prior-box size attrs)
                boxes.append((s, s))
        for ar in ars:
            if abs(ar - 1.0) < 1e-6:
                continue
            boxes.append((ms * float(np.sqrt(ar)), ms / float(np.sqrt(ar))))
    num = len(boxes)
    cx = (jnp.arange(fw, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(fh, dtype=jnp.float32) + offset) * step_h
    gy, gx = jnp.meshgrid(cy, cx, indexing="ij")
    wh = jnp.asarray(boxes, jnp.float32)  # [num, 2]
    bx = jnp.stack([
        (gx[..., None] - wh[None, None, :, 0] / 2) / iw,
        (gy[..., None] - wh[None, None, :, 1] / 2) / ih,
        (gx[..., None] + wh[None, None, :, 0] / 2) / iw,
        (gy[..., None] + wh[None, None, :, 1] / 2) / ih,
    ], axis=-1)  # [fh, fw, num, 4]
    if clip:
        bx = jnp.clip(bx, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           bx.shape)
    return bx, var


@op()
def generate_proposals(scores, bbox_deltas, im_shape, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False):
    """RPN proposal generation. scores [N,A,H,W], deltas [N,A*4,H,W]."""
    n, a, h, w = scores.shape
    anc = anchors.reshape(-1, 4).astype(jnp.float32)
    var = variances.reshape(-1, 4).astype(jnp.float32)

    def per_image(sc, dl, imshape):
        s = jnp.transpose(sc, (1, 2, 0)).reshape(-1)  # [H*W*A]
        d = jnp.transpose(dl.reshape(a, 4, h, w), (2, 3, 0, 1)).reshape(-1, 4)
        k = min(pre_nms_top_n, s.shape[0])
        top_s, idx = jax.lax.top_k(s, k)
        d = d[idx]
        an = anc[idx]
        va = var[idx]
        aw = an[:, 2] - an[:, 0] + (1.0 if pixel_offset else 0.0)
        ah = an[:, 3] - an[:, 1] + (1.0 if pixel_offset else 0.0)
        acx = an[:, 0] + aw / 2
        acy = an[:, 1] + ah / 2
        cx = va[:, 0] * d[:, 0] * aw + acx
        cy = va[:, 1] * d[:, 1] * ah + acy
        bw = jnp.exp(jnp.minimum(va[:, 2] * d[:, 2], 10.0)) * aw
        bh = jnp.exp(jnp.minimum(va[:, 3] * d[:, 3], 10.0)) * ah
        off = 1.0 if pixel_offset else 0.0
        props = jnp.stack([cx - bw / 2, cy - bh / 2,
                           cx + bw / 2 - off, cy + bh / 2 - off], axis=1)
        props = jnp.clip(props,
                         jnp.zeros((4,)),
                         jnp.asarray([imshape[1] - 1, imshape[0] - 1,
                                      imshape[1] - 1, imshape[0] - 1]))
        ws = props[:, 2] - props[:, 0] + off
        hs = props[:, 3] - props[:, 1] + off
        valid = (ws >= min_size) & (hs >= min_size)
        s2 = jnp.where(valid, top_s, -jnp.inf)
        keep = _nms_mask(props, s2, nms_thresh) & valid
        s3 = jnp.where(keep, s2, -jnp.inf)
        kk = min(post_nms_top_n, s3.shape[0])
        fs, fi = jax.lax.top_k(s3, kk)
        return props[fi], fs, jnp.isfinite(fs).sum()

    rois, rscores, cnt = jax.vmap(per_image)(
        scores.astype(jnp.float32), bbox_deltas.astype(jnp.float32),
        im_shape.astype(jnp.float32))
    kk = rois.shape[1]
    return rois.reshape(-1, 4), rscores.reshape(-1, 1), cnt.astype(jnp.int32)


@op()
def distribute_fpn_proposals(fpn_rois, rois_num=None, min_level=2,
                             max_level=5, refer_level=4, refer_scale=224,
                             pixel_offset=False):
    rois = fpn_rois.astype(jnp.float32)
    off = 1.0 if pixel_offset else 0.0
    ws = rois[:, 2] - rois[:, 0] + off
    hs = rois[:, 3] - rois[:, 1] + off
    scale = jnp.sqrt(jnp.maximum(ws * hs, 1e-6))
    lvl = jnp.floor(jnp.log2(scale / refer_scale + 1e-6)) + refer_level
    lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
    n_levels = max_level - min_level + 1
    outs, idxs, nums = [], [], []
    order = jnp.argsort(lvl, stable=True)
    for li in range(n_levels):
        mask = lvl == (min_level + li)
        cnt = mask.sum()
        sel = jnp.where(mask, jnp.arange(rois.shape[0]), rois.shape[0])
        sel = jnp.sort(sel)
        sel_c = jnp.clip(sel, 0, rois.shape[0] - 1)
        outs.append(jnp.where((sel < rois.shape[0])[:, None],
                              rois[sel_c], 0.0))
        idxs.append(sel)
        nums.append(cnt)
    restore = jnp.argsort(jnp.concatenate(
        [jnp.where(i < rois.shape[0], i, 10 ** 9) for i in idxs]))
    return outs, restore[:, None].astype(jnp.int32), \
        [n.astype(jnp.int32) for n in nums]


# ---------------------------------------------------------------- YOLO ops

@op()
def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    n, c, h, w = x.shape
    na = len(anchors) // 2
    an = jnp.asarray(anchors, jnp.float32).reshape(na, 2)
    if iou_aware:
        ious = jax.nn.sigmoid(x[:, :na].astype(jnp.float32))
        x = x[:, na:]
    pred = x.reshape(n, na, 5 + class_num, h, w).astype(jnp.float32)
    gx = jnp.arange(w, dtype=jnp.float32)
    gy = jnp.arange(h, dtype=jnp.float32)
    bx = (jax.nn.sigmoid(pred[:, :, 0]) * scale_x_y
          - 0.5 * (scale_x_y - 1) + gx[None, None, None, :]) / w
    by = (jax.nn.sigmoid(pred[:, :, 1]) * scale_x_y
          - 0.5 * (scale_x_y - 1) + gy[None, None, :, None]) / h
    input_w = downsample_ratio * w
    input_h = downsample_ratio * h
    bw = jnp.exp(pred[:, :, 2]) * an[None, :, 0, None, None] / input_w
    bh = jnp.exp(pred[:, :, 3]) * an[None, :, 1, None, None] / input_h
    conf = jax.nn.sigmoid(pred[:, :, 4])
    if iou_aware:
        conf = conf ** (1 - iou_aware_factor) * \
            ious ** iou_aware_factor
    probs = jax.nn.sigmoid(pred[:, :, 5:]) * conf[:, :, None]
    score_mask = conf > conf_thresh
    imh = img_size[:, 0].astype(jnp.float32)[:, None, None, None]
    imw = img_size[:, 1].astype(jnp.float32)[:, None, None, None]
    x1 = (bx - bw / 2) * imw
    y1 = (by - bh / 2) * imh
    x2 = (bx + bw / 2) * imw
    y2 = (by + bh / 2) * imh
    if clip_bbox:
        x1 = jnp.clip(x1, 0, imw - 1)
        y1 = jnp.clip(y1, 0, imh - 1)
        x2 = jnp.clip(x2, 0, imw - 1)
        y2 = jnp.clip(y2, 0, imh - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1)
    boxes = jnp.where(score_mask[..., None], boxes, 0.0)
    boxes = boxes.reshape(n, -1, 4)
    scores = jnp.where(score_mask[:, :, None], probs, 0.0)
    scores = jnp.transpose(scores, (0, 1, 3, 4, 2)).reshape(
        n, -1, class_num)
    return boxes, scores


@op()
def yolo_loss(x, gt_box, gt_label, gt_score=None, anchors=(),
              anchor_mask=(), class_num=1, ignore_thresh=0.7,
              downsample_ratio=32, use_label_smooth=True, scale_x_y=1.0):
    """YOLOv3 loss: xy/wh/obj/cls terms; [N,C,H,W] preds, [N,B,4] gt."""
    n, c, h, w = x.shape
    na = len(anchor_mask)
    an_all = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)
    an = an_all[jnp.asarray(anchor_mask, jnp.int32)]
    pred = x.reshape(n, na, 5 + class_num, h, w).astype(jnp.float32)
    input_size = downsample_ratio * h
    gtb = gt_box.astype(jnp.float32)  # [N,B,4] cx,cy,w,h normalized
    b = gtb.shape[1]

    px = jax.nn.sigmoid(pred[:, :, 0])
    py = jax.nn.sigmoid(pred[:, :, 1])
    pw = pred[:, :, 2]
    ph = pred[:, :, 3]
    pobj = pred[:, :, 4]
    pcls = pred[:, :, 5:]

    gi = jnp.clip((gtb[..., 0] * w).astype(jnp.int32), 0, w - 1)  # [N,B]
    gj = jnp.clip((gtb[..., 1] * h).astype(jnp.int32), 0, h - 1)
    valid = (gtb[..., 2] > 0) & (gtb[..., 3] > 0)

    # best anchor per gt (iou of wh only, against all anchors)
    gw = gtb[..., 2] * input_size
    gh = gtb[..., 3] * input_size
    inter = jnp.minimum(gw[..., None], an_all[None, None, :, 0]) * \
        jnp.minimum(gh[..., None], an_all[None, None, :, 1])
    union = gw[..., None] * gh[..., None] + \
        an_all[None, None, :, 0] * an_all[None, None, :, 1] - inter
    best = jnp.argmax(inter / jnp.maximum(union, 1e-9), axis=-1)  # [N,B]
    mask_list = jnp.asarray(anchor_mask, jnp.int32)
    an_idx = jnp.argmax(best[..., None] == mask_list[None, None, :],
                        axis=-1)  # position in mask
    responsible = jnp.any(best[..., None] == mask_list[None, None, :],
                          axis=-1) & valid

    tx = gtb[..., 0] * w - gi.astype(jnp.float32)
    ty = gtb[..., 1] * h - gj.astype(jnp.float32)
    tw = jnp.log(jnp.maximum(gw / jnp.maximum(an[an_idx][..., 0], 1e-9),
                             1e-9))
    th = jnp.log(jnp.maximum(gh / jnp.maximum(an[an_idx][..., 1], 1e-9),
                             1e-9))
    tscale = 2.0 - gtb[..., 2] * gtb[..., 3]
    score_w = (gt_score.astype(jnp.float32) if gt_score is not None
               else jnp.ones((n, b), jnp.float32))

    bidx = jnp.arange(n)[:, None].repeat(b, 1)
    sel = (bidx, an_idx, gj, gi)
    wgt = jnp.where(responsible, tscale * score_w, 0.0)

    def bce(p, t):
        return -(t * jnp.log(jnp.clip(p, 1e-9, 1.0))
                 + (1 - t) * jnp.log(jnp.clip(1 - p, 1e-9, 1.0)))

    loss_xy = (bce(px[sel], tx) + bce(py[sel], ty)) * wgt
    loss_wh = (jnp.abs(pw[sel] - tw) + jnp.abs(ph[sel] - th)) * wgt

    # objectness: positive at responsible cells; predictions whose decoded
    # box overlaps any gt above ignore_thresh are excluded from the
    # negative term (YOLOv3 semantics; reference kernel
    # paddle/phi/kernels/cpu/yolo_loss_kernel.cc CalcObjnessLoss)
    obj_t = jnp.zeros((n, na, h, w))
    obj_t = obj_t.at[sel].max(jnp.where(responsible, score_w, 0.0))
    obj_mask = jnp.zeros((n, na, h, w), jnp.bool_)
    obj_mask = obj_mask.at[sel].max(responsible)

    gx_grid = jnp.arange(w, dtype=jnp.float32)
    gy_grid = jnp.arange(h, dtype=jnp.float32)
    pbx = (px + gx_grid[None, None, None, :]) / w
    pby = (py + gy_grid[None, None, :, None]) / h
    pbw = jnp.exp(jnp.clip(pw, -10, 10)) * an[None, :, 0, None, None] \
        / input_size
    pbh = jnp.exp(jnp.clip(ph, -10, 10)) * an[None, :, 1, None, None] \
        / input_size
    # IoU of each predicted box vs each gt (center-size, normalized coords)
    p1x = (pbx - pbw / 2)[..., None]
    p1y = (pby - pbh / 2)[..., None]
    p2x = (pbx + pbw / 2)[..., None]
    p2y = (pby + pbh / 2)[..., None]
    g1x = (gtb[..., 0] - gtb[..., 2] / 2)[:, None, None, None, :]
    g1y = (gtb[..., 1] - gtb[..., 3] / 2)[:, None, None, None, :]
    g2x = (gtb[..., 0] + gtb[..., 2] / 2)[:, None, None, None, :]
    g2y = (gtb[..., 1] + gtb[..., 3] / 2)[:, None, None, None, :]
    iw = jnp.maximum(jnp.minimum(p2x, g2x) - jnp.maximum(p1x, g1x), 0.0)
    ih = jnp.maximum(jnp.minimum(p2y, g2y) - jnp.maximum(p1y, g1y), 0.0)
    inter_pg = iw * ih
    union_pg = (pbw * pbh)[..., None] + \
        (gtb[..., 2] * gtb[..., 3])[:, None, None, None, :] - inter_pg
    best_iou = jnp.max(jnp.where(valid[:, None, None, None, :],
                                 inter_pg / jnp.maximum(union_pg, 1e-9),
                                 0.0), axis=-1)  # [N,na,H,W]
    ignore = (best_iou > ignore_thresh) & ~obj_mask

    loss_obj = bce(jax.nn.sigmoid(pobj), obj_t)
    loss_obj = jnp.where(ignore, 0.0,
                         jnp.where(obj_mask | (obj_t == 0), loss_obj, 0.0))

    smooth = 1.0 / class_num if use_label_smooth else 0.0
    cls_t = jnp.full((n, b, class_num), smooth, jnp.float32)
    lbl = jnp.clip(gt_label.astype(jnp.int32), 0, class_num - 1)
    cls_t = cls_t.at[jnp.arange(n)[:, None], jnp.arange(b)[None, :], lbl] \
        .set(1.0 - smooth)
    pc = jax.nn.sigmoid(jnp.transpose(pcls, (0, 1, 3, 4, 2))[sel])
    loss_cls = jnp.sum(bce(pc, cls_t), -1) * jnp.where(responsible, 1.0, 0.0)

    total = (loss_xy.sum((1,)) + loss_wh.sum((1,))
             + loss_obj.sum((1, 2, 3)) + loss_cls.sum((1,)))
    return total


# ------------------------------------------------------- deformable conv

@op()
def deformable_conv(x, offset, weight, mask=None, strides=(1, 1),
                    paddings=(0, 0), dilations=(1, 1),
                    deformable_groups=1, groups=1, im2col_step=64):
    """Deformable conv v1/v2 via bilinear-sampled im2col + matmul (MXU)."""
    n, cin, h, w = x.shape
    cout, cin_g, kh, kw = weight.shape
    sh, sw = strides
    ph, pw = paddings
    dh, dw = dilations
    oh = (h + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    ow = (w + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    off = offset.astype(jnp.float32).reshape(
        n, deformable_groups, kh * kw, 2, oh, ow)
    base_y = (jnp.arange(oh) * sh - ph)[:, None] + \
        (jnp.arange(kh) * dh)[None, :]  # [oh, kh]
    base_x = (jnp.arange(ow) * sw - pw)[:, None] + \
        (jnp.arange(kw) * dw)[None, :]  # [ow, kw]
    ch_per_dg = cin // deformable_groups

    def per_image(xi, offi, mi):
        cols = []
        for dg in range(deformable_groups):
            feat = xi[dg * ch_per_dg:(dg + 1) * ch_per_dg].astype(jnp.float32)
            # sample coords [kh,kw,oh,ow]
            oy = offi[dg, :, 0].reshape(kh, kw, oh, ow)
            ox = offi[dg, :, 1].reshape(kh, kw, oh, ow)
            yy = base_y.T[:, None, :, None] + oy  # [kh,kw,oh,ow]
            xx = base_x.T[None, :, None, :] + ox
            # reference dmc_im2col_bilinear: each of the four taps
            # contributes ONLY if in-bounds (partial weights at the
            # border) — clipping coords first would give the border
            # pixel full weight (caught by the round-3 numpy reference)
            y0 = jnp.floor(yy)
            x0 = jnp.floor(xx)
            dy = yy - y0
            dx = xx - x0
            v = jnp.zeros((ch_per_dg,) + yy.shape, jnp.float32)
            # NB: tap vars must not shadow per_image's `xi` image arg
            # (the dg>0 iteration would slice a coordinate array)
            for ty, wy in ((y0, 1 - dy), (y0 + 1, dy)):
                for tx, wx in ((x0, 1 - dx), (x0 + 1, dx)):
                    tap_ok = (ty >= 0) & (ty < h) & (tx >= 0) & (tx < w)
                    yc = jnp.clip(ty, 0, h - 1).astype(jnp.int32)
                    xc = jnp.clip(tx, 0, w - 1).astype(jnp.int32)
                    tap = feat[:, yc, xc]  # [C, kh, kw, oh, ow]
                    v = v + jnp.where(tap_ok[None],
                                      (wy * wx)[None] * tap, 0.0)
            if mi is not None:
                mm = mi[dg].reshape(kh, kw, oh, ow)
                v = v * mm[None]
            cols.append(v)
        return jnp.concatenate(cols, axis=0)  # [cin,kh,kw,oh,ow]

    if mask is not None:
        mi = mask.astype(jnp.float32).reshape(
            n, deformable_groups, kh * kw, oh, ow)
        col = jax.vmap(per_image)(x, off, mi)
    else:
        col = jax.vmap(lambda xi, offi: per_image(xi, offi, None))(x, off)
    wmat = weight.reshape(cout, cin_g * kh * kw).astype(jnp.float32)
    cpg = cin // groups
    opg = cout // groups
    outs = []
    for g in range(groups):
        cg = col[:, g * cpg:(g + 1) * cpg].reshape(n, cpg * kh * kw, oh * ow)
        wg = wmat[g * opg:(g + 1) * opg]
        outs.append(jnp.einsum("ok,nkl->nol", wg, cg))
    out = jnp.concatenate(outs, axis=1).reshape(n, cout, oh, ow)
    return out.astype(x.dtype)


# --------------------------------------------------------------- fold etc.

@op()
def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1):
    """col2im: x [N, C*kh*kw, L] → [N, C, H, W] (inverse of unfold)."""
    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    oh, ow = _pair(output_sizes)
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    ph, pw = _pair(paddings)
    dh, dw = _pair(dilations)
    n, ckk, loc = x.shape
    c = ckk // (kh * kw)
    lh = (oh + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    lw = (ow + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    xr = x.reshape(n, c, kh, kw, lh, lw)
    out = jnp.zeros((n, c, oh + 2 * ph, ow + 2 * pw), x.dtype)
    for i in range(kh):
        for j in range(kw):
            ys = i * dh
            xs = j * dw
            out = out.at[:, :, ys:ys + lh * sh:sh, xs:xs + lw * sw:sw].add(
                xr[:, :, i, j])
    return out[:, :, ph:ph + oh, pw:pw + ow]


@op()
def channel_shuffle(x, groups, data_format="NCHW"):
    if data_format == "NCHW":
        n, c, h, w = x.shape
        return x.reshape(n, groups, c // groups, h, w) \
            .transpose(0, 2, 1, 3, 4).reshape(n, c, h, w)
    n, h, w, c = x.shape
    return x.reshape(n, h, w, groups, c // groups) \
        .transpose(0, 1, 2, 4, 3).reshape(n, h, w, c)


def decode_jpeg(x, mode="unchanged", name=None):
    """Host-side JPEG decode (reference: paddle/phi/kernels/gpu/
    decode_jpeg_kernel.cu uses nvjpeg; TPU has no device JPEG engine, so this
    is a host op feeding the input pipeline)."""
    import io as _io
    data = np.asarray(x, dtype=np.uint8).tobytes()  # noqa: H001 (host JPEG decode by design)
    try:
        from PIL import Image  # noqa: PLC0415
    except ImportError as e:  # pragma: no cover
        raise NotImplementedError(
            "decode_jpeg requires Pillow on the host") from e
    img = Image.open(_io.BytesIO(data))
    if mode != "unchanged":
        img = img.convert(mode.upper() if mode != "gray" else "L")
    arr = np.asarray(img)  # noqa: H001 (host JPEG decode by design)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    from ..core.tensor import Tensor
    return Tensor(jnp.asarray(arr))


from .registry import register_external  # noqa: E402
register_external("decode_jpeg", decode_jpeg)
