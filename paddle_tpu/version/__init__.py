"""paddle.version parity (reference python/paddle/version.py, generated
by setup.py at build time)."""

full_version = "0.2.0"
major = "0"
minor = "2"
patch = "0"
rc = "0"
cuda_version = "False"   # no CUDA anywhere — TPU-native build
cudnn_version = "False"
tpu = True
commit = "unknown"
with_pip = True

__all__ = ["full_version", "major", "minor", "patch", "rc", "commit",
           "cuda", "cudnn", "show"]


def cuda():
    return cuda_version


def cudnn():
    return cudnn_version


def show():
    print(f"full_version: {full_version}")
    print(f"commit: {commit}")
    print("tpu: True (jax/XLA compute, no CUDA)")
