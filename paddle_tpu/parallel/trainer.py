"""SpmdTrainStep: the hybrid-parallel (dp × pp × mp [+sp]) training step.

One compiled XLA program per step over the fleet Mesh:
  embed (GSPMD dp/mp) → spmd_pipeline over 'pp' (shard_map+ppermute) →
  head+loss (GSPMD) → jax.grad → grad clip → optimizer update.
This is the TPU replacement for the reference's whole Fleet stack composition
(HybridParallelOptimizer + PipelineParallel + TensorParallel + sharding
wrappers — SURVEY §3.4): the strategy lives in shardings, the compiler owns
the collectives.

ZeRO/sharding stages map to optimizer-state sharding specs (stage 1), handled
here by sharding optimizer state over the 'sharding' axis when present —
stage 2/3 semantics (grad/param sharding) are with_sharding_constraint
choices, not separate machinery (reference group_sharded_stage{2,3}.py
dissolves into GSPMD).
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from ..framework.random import get_rng_key, key_stream
from .pipeline import spmd_pipeline


def _spec_from_axes(mesh, axes, ndim):
    if axes is None:
        spec = [None] * ndim
    else:
        spec = [a if (a is None or a in mesh.axis_names) else None
                for a in axes]
        spec = spec + [None] * (ndim - len(spec))
    return P(*spec)


def _shard_opt_state_spec(mesh, param_spec, ndim, zero_axis="sharding"):
    """ZeRO stage-1: optimizer state sharded over ``zero_axis`` on the
    first dim not already sharded (falls back to the param's own spec).

    ``zero_axis="dp"`` folds sharding into the data-parallel axis — the
    reference's sharding group IS a subdivision of the dp replicas
    (group_sharded stage-1 semantics) — for meshes without a dedicated
    'sharding' axis."""
    if not zero_axis or zero_axis not in mesh.axis_names or \
            mesh.shape.get(zero_axis, 1) == 1:
        return param_spec
    spec = list(param_spec) + [None] * (ndim - len(param_spec))
    for i, s in enumerate(spec):
        if s is None:
            spec[i] = zero_axis
            return P(*spec)
    return param_spec


class SpmdTrainStep:
    """Compiled hybrid-parallel train step for models exposing
    ``functional_decompose()`` (see models/gpt.py).

    Usage::
        trainer = SpmdTrainStep(model, opt, mesh, n_microbatches=4)
        loss = trainer.step(input_ids, labels)
    """

    def __init__(self, model, optimizer, mesh, n_microbatches=1,
                 sequence_parallel=False, remat=False, zero_stage=1,
                 virtual_pp=1, scaler=None, zero_axis=None):
        self.model = model
        self.optimizer = optimizer
        self.mesh = mesh
        self.n_microbatches = n_microbatches
        self.sequence_parallel = sequence_parallel
        self.remat = remat
        self.virtual_pp = virtual_pp
        # ZeRO axis: a dedicated 'sharding' mesh axis when present, else
        # opt-in folding into 'dp' (zero_axis="dp") — reference sharding
        # groups subdivide the data-parallel replicas
        if zero_axis is None:
            zero_axis = "sharding"
        self.zero_axis = zero_axis if zero_stage else None
        # loss scaling composed into the compiled hybrid step (the fleet
        # distributed_scaler role, fleet/scaler.py:28 — found-inf detection
        # is global automatically: grads are global arrays under GSPMD)
        self.scaler = scaler if (scaler is not None and scaler.is_enable()) \
            else None
        if self.scaler is not None:
            from ..amp import scaler_init_state
            self._scaler_state = scaler_init_state(self.scaler)
            self.scaler._compiled_state = self._scaler_state
        else:
            self._scaler_state = None

        d = model.functional_decompose()
        self.fns = d["fns"]
        self.num_layers = d["num_layers"]
        params = d["params"]
        specs = d["specs"]

        # Interleaved pipeline: permute the stacked layer dim ONCE here so
        # each stage's round-robin chunks land contiguously under the P('pp')
        # sharding — doing it inside the jitted step would re-gather half the
        # block weights across stages every step.
        self._layer_perm = None
        pp_deg = mesh.shape.get("pp", 1)
        if virtual_pp > 1 and pp_deg > 1:
            from .pipeline import interleave_permutation
            self._layer_perm = interleave_permutation(
                self.num_layers, pp_deg, virtual_pp)
            params = dict(params)
            params["blocks"] = jax.tree_util.tree_map(
                lambda leaf: leaf[self._layer_perm], params["blocks"])

        # build NamedShardings per leaf
        def shardings_for(p_tree, s_tree):
            out = {}
            for k, v in p_tree.items():
                spec = _spec_from_axes(mesh, s_tree.get(k), v.ndim)
                out[k] = NamedSharding(mesh, spec)
            return out

        self.param_shardings = {
            "embed": shardings_for(params["embed"], specs["embed"]),
            "blocks": shardings_for(params["blocks"], specs["blocks"]),
            "head": shardings_for(params["head"], specs["head"]),
        }
        # place params
        self.params = jax.tree_util.tree_map(
            lambda v, s: jax.device_put(v, s), params, self.param_shardings)

        # optimizer state: mirror param sharding (+ ZeRO over 'sharding' axis)
        self.opt_state = optimizer.init_state_pytree(self.params)

        def opt_shard(path_sh, state):
            return jax.tree_util.tree_map(
                lambda sv: jax.device_put(
                    sv, NamedSharding(
                        mesh,
                        _shard_opt_state_spec(
                            mesh, path_sh.spec, sv.ndim, self.zero_axis)
                        if sv.ndim else P())),
                state)

        self.opt_state = jax.tree_util.tree_map(
            opt_shard, self.param_shardings, self.opt_state,
            is_leaf=lambda x: isinstance(x, NamedSharding))

        # batch parallelism rides dp AND a dedicated sharding axis — the
        # sharding group is extra data parallelism (reference group_sharded)
        self._batch_axes = tuple(
            a for a in ("dp", "sharding")
            if mesh.shape.get(a, 1) > 1) or None
        if self._batch_axes is not None and len(self._batch_axes) == 1:
            self._batch_axes = self._batch_axes[0]
        self.batch_sharding = NamedSharding(mesh, P(self._batch_axes))
        self._step_count = 0
        self._compiled = None

    # ---- the step program ----
    def _build(self):
        embed_fn, block_fn, head_fn, loss_fn = self.fns
        mesh = self.mesh
        n_micro = self.n_microbatches
        optimizer = self.optimizer
        grad_clip = optimizer._grad_clip
        seq_spec = P(self._batch_axes, "mp", None) \
            if (self.sequence_parallel and "mp" in mesh.axis_names) \
            else P(self._batch_axes, None, None)
        blk = block_fn
        if self.remat:
            blk = jax.checkpoint(block_fn)

        def forward(params, input_ids, labels, key):
            key, pipe_key = jax.random.split(key)
            with key_stream(key):
                h = embed_fn(params["embed"], input_ids)
                h = jax.lax.with_sharding_constraint(
                    h, NamedSharding(mesh, seq_spec))
                h = spmd_pipeline(blk, params["blocks"], h, mesh=mesh,
                                  n_microbatches=n_micro, rng_key=pipe_key,
                                  activation_spec=seq_spec,
                                  virtual_pp=self.virtual_pp,
                                  prepermuted=True)
                h = jax.lax.with_sharding_constraint(
                    h, NamedSharding(mesh, seq_spec))
                logits = head_fn(params["head"], h, params["embed"])
                return loss_fn(logits, labels)

        def step_fn(params, opt_state, step, lr, key, input_ids, labels):
            loss, grads = jax.value_and_grad(forward)(params, input_ids,
                                                      labels, key)
            if grad_clip is not None:
                grads = grad_clip.clip_pytree(grads)
            new_params, new_opt = optimizer.apply_gradients_pytree(
                params, grads, opt_state, step, lr=lr)
            return loss, new_params, new_opt

        scaler = self.scaler

        def step_fn_scaled(params, opt_state, step, lr, key, input_ids,
                           labels, scaler_state):
            from ..amp import scaler_guarded_update

            def scaled(params, input_ids, labels, key):
                l = forward(params, input_ids, labels, key)
                return l * scaler_state["scale"].astype(l.dtype), l

            (_, loss), grads = jax.value_and_grad(scaled, has_aux=True)(
                params, input_ids, labels, key)
            new_params, new_opt, new_sstate = scaler_guarded_update(
                scaler, scaler_state, grads, grad_clip, optimizer,
                params, opt_state, step, lr)
            return loss, new_params, new_opt, new_sstate

        self._compiled = jax.jit(
            step_fn_scaled if scaler is not None else step_fn,
            donate_argnums=(0, 1))

    def step(self, input_ids, labels):
        if self._compiled is None:
            self._build()
        self._step_count += 1
        ids = input_ids._data if isinstance(input_ids, Tensor) else input_ids
        lbl = labels._data if isinstance(labels, Tensor) else labels
        ids = jax.device_put(ids, self.batch_sharding)
        lbl = jax.device_put(lbl, self.batch_sharding)
        lr = jnp.float32(self.optimizer.get_lr())
        key = get_rng_key()
        with self.mesh:
            if self.scaler is not None:
                loss, self.params, self.opt_state, new_sstate = \
                    self._compiled(self.params, self.opt_state,
                                   jnp.int32(self._step_count), lr, key,
                                   ids, lbl, self.scaler._compiled_state)
                self.scaler._compiled_state = new_sstate
            else:
                loss, self.params, self.opt_state = self._compiled(
                    self.params, self.opt_state, jnp.int32(self._step_count),
                    lr, key, ids, lbl)
        return Tensor(loss)

    __call__ = step

    def _canonical_params(self):
        """Params with the stacked-layer dim in model order (the interleave
        permutation undone) — the layout checkpoints and the model use."""
        if self._layer_perm is None:
            return self.params
        inv = np.argsort(self._layer_perm)
        out = dict(self.params)
        out["blocks"] = jax.tree_util.tree_map(
            lambda leaf: leaf[inv], self.params["blocks"])
        return out

    def sync_to_model(self):
        self.model.load_stacked(self._canonical_params())

    def state_dict(self):
        return {"params": self._canonical_params(),
                "opt_state": self.opt_state,
                "step": self._step_count}
