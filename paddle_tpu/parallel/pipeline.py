"""SPMD pipeline parallelism: the microbatch loop compiled INTO the program.

The reference drives 1F1B from the host (PipelineParallel at
meta_parallel/pipeline_parallel.py:188, NCCL P2P per microbatch edge).  On TPU
the whole schedule lives inside one XLA program: a ``shard_map`` manual only
over the 'pp' mesh axis (dp/mp stay under GSPMD via ``axis_names``), a
``lax.scan`` over schedule ticks, and ``lax.ppermute`` moving activations
stage→stage over ICI.  ``jax.grad`` through the scan yields the reverse
pipeline automatically — backward scheduling falls out of AD instead of being
hand-written (the subtle part of the reference's interleaved 1F1B).
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..framework.random import key_stream


def _layer_scan(block_fn, x, stacked_params, rng_key):
    """Scan over stacked layers, threading a fresh dropout key per layer."""
    n_layers = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    keys = jax.random.split(rng_key, n_layers) if rng_key is not None else None

    def body(h, xs):
        if keys is None:
            return block_fn(xs, h), None
        lp, k = xs
        with key_stream(k):
            return block_fn(lp, h), None

    xs = stacked_params if keys is None else (stacked_params, keys)
    out, _ = lax.scan(body, x, xs)
    return out


def spmd_pipeline(block_fn, stacked_params, x, *, mesh, n_microbatches,
                  axis="pp", rng_key=None, activation_spec=None):
    """Run ``x`` through pipeline stages inside the current jit trace.

    Args:
      block_fn: pure ``(layer_params, hidden) -> hidden`` for ONE layer.
      stacked_params: pytree with leaves ``[num_layers, ...]`` — will be
        split so each stage owns ``num_layers // pp`` consecutive layers.
      x: activations ``[batch, ...]`` (a global array; dp/mp shardings stay
        under GSPMD).
      n_microbatches: must divide batch.
    Returns activations after all layers, same shape as x.
    """
    pp = mesh.shape[axis]
    n_layers = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if pp == 1:
        return _layer_scan(block_fn, x, stacked_params, rng_key)

    m = n_microbatches
    batch = x.shape[0]
    assert batch % m == 0, f"batch {batch} not divisible by microbatches {m}"
    assert n_layers % pp == 0, \
        f"num_layers {n_layers} not divisible by pp degree {pp}"

    other_axes = frozenset(n for n in mesh.axis_names if n != axis)

    def stage_fn(local_params, x_local):
        # local_params leaves: [layers_per_stage, ...]; x_local: [m, mb, ...]
        stage = lax.axis_index(axis)
        # decorrelate dropout across stages and ticks
        stage_key = (jax.random.fold_in(rng_key, stage)
                     if rng_key is not None else None)

        def run_stage(h, tick):
            k = (jax.random.fold_in(stage_key, tick)
                 if stage_key is not None else None)
            return _layer_scan(block_fn, h, local_params, k)

        state = jnp.zeros_like(x_local[0])
        outputs = jnp.zeros_like(x_local)
        perm = [(i, (i + 1) % pp) for i in range(pp)]

        def tick(carry, t):
            state, outputs = carry
            # stage 0 ingests microbatch t while t < m
            inject = x_local[jnp.clip(t, 0, m - 1)]
            state = jnp.where((stage == 0) & (t < m), inject, state)
            out = run_stage(state, t)
            # last stage emits microbatch (t - pp + 1)
            mb_idx = t - (pp - 1)
            valid = (stage == pp - 1) & (mb_idx >= 0) & (mb_idx < m)
            outputs = jnp.where(
                valid,
                lax.dynamic_update_index_in_dim(
                    outputs, out, jnp.clip(mb_idx, 0, m - 1), 0),
                outputs)
            state = lax.ppermute(out, axis, perm)
            return (state, outputs), None

        (state, outputs), _ = lax.scan(tick, (state, outputs),
                                       jnp.arange(m + pp - 1))
        # replicate the last stage's outputs to every stage
        outputs = lax.psum(
            jnp.where(stage == pp - 1, outputs, jnp.zeros_like(outputs)),
            axis)
        return outputs

    mapped = jax.shard_map(
        stage_fn, mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P(axis), stacked_params),
                  P()),
        out_specs=P(),
        axis_names=frozenset({axis}),
        check_vma=False)

    x_micro = x.reshape((m, batch // m) + x.shape[1:])
    if activation_spec is not None:
        # Keep the caller's activation sharding (e.g. dp on batch, mp on
        # seq) on the microbatched layout instead of clobbering it — a
        # mismatched constraint here cannot be transposed by XLA in the
        # backward pass and triggers involuntary full rematerialization.
        micro_spec = P(None, *activation_spec)
        x_micro = lax.with_sharding_constraint(
            x_micro, jax.sharding.NamedSharding(mesh, micro_spec))
    elif "dp" in mesh.axis_names:
        x_micro = lax.with_sharding_constraint(
            x_micro, jax.sharding.NamedSharding(
                mesh, P(None, "dp", *([None] * (x_micro.ndim - 2)))))
    out = mapped(stacked_params, x_micro)
    return out.reshape(x.shape)
