"""paddle_tpu.jit: the dygraph→compiled bridge.

Replaces the reference's dy2static stack (``paddle.jit.to_static`` at
python/paddle/jit/api.py:233: AST transformers → ProgramDesc →
PartialProgramLayer → InterpreterCore).  On TPU there is no program IR of our
own: ``to_static`` traces the eager code with jax tracers flowing through the
same op implementations and compiles via XLA.  ConcreteProgram analog = the
jaxpr cached inside jax.jit; StandaloneExecutor analog = PjRt executable cache.

Key pieces:
- ``functional_call(layer, values, *args)`` — run a Layer with its
  parameters/buffers substituted from a pytree (torch.func-style), the
  functionalization primitive everything else builds on.
- ``to_static(fn_or_layer)`` — compile forward.
- ``TrainStep(model, loss_fn, opt)`` — whole training step (fwd+bwd+optimizer)
  as ONE compiled XLA program: the performance path matching the reference's
  "everything under jit" north star.
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..framework import mode
from ..framework.random import get_rng_key, key_stream
from ..nn.layer_base import Layer

_is_tensor = lambda x: isinstance(x, Tensor)


def _bind(layer, values):
    """Swap state_dict tensors' storage to ``values``; return restore list."""
    sd = layer.state_dict()
    saved = []
    for name, arr in values.items():
        t = sd[name]
        saved.append((t, t._data))
        t._data = arr
    return saved, sd


def _restore(saved):
    for t, data in saved:
        t._data = data


def functional_call(layer, values, *args, return_buffers=False,
                    forward_fn=None, **kwargs):
    """Run ``layer(*args, **kwargs)`` with parameters/buffers from ``values``
    (dict name -> jax array).  Inputs may be Tensors or jax arrays.  Returns
    output (jax-array pytree); with ``return_buffers=True`` also returns the
    possibly-updated buffer values (BatchNorm running stats etc.).
    ``forward_fn`` overrides the callable (used by to_static to avoid
    re-entering its own compiled forward)."""
    saved, sd = _bind(layer, values)
    call = forward_fn if forward_fn is not None else layer
    try:
        targs = [Tensor(a) if not isinstance(a, Tensor) and
                 isinstance(a, (jax.Array, np.ndarray)) else a for a in args]
        with mode.grad_enabled(False):
            out = call(*targs, **kwargs)
        out_data = jax.tree_util.tree_map(
            lambda t: t._data if isinstance(t, Tensor) else t, out,
            is_leaf=_is_tensor)
        if return_buffers:
            buf = {name: t._data for name, t in sd.items() if name in values}
            return out_data, buf
        return out_data
    finally:
        _restore(saved)


def _split_state(layer):
    """Trainable params vs frozen state (non-trainable params + buffers)."""
    params, others = {}, {}
    for name, t in layer.state_dict().items():
        if isinstance(t, Tensor) and not t.stop_gradient:
            params[name] = t._data
        else:
            others[name] = t._data
    return params, others


class StaticFunction:
    """Compiled forward wrapper (ConcreteProgram/PartialProgramLayer analog,
    reference python/paddle/jit/dy2static/program_translator.py)."""

    def __init__(self, function, layer=None, ir_passes=None):
        self._function = function
        self._layer = layer
        self._cache = {}
        # jaxpr pattern-rewrite passes (framework/ir.py): None/False off,
        # True = all registered, or an explicit sequence of pass names
        self._ir_passes = ir_passes

    def __call__(self, *args, **kwargs):
        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs),
                                                     is_leaf=_is_tensor)
        t_pos = tuple(i for i, l in enumerate(leaves) if isinstance(l, Tensor))
        datas = [leaves[i]._data for i in t_pos]
        static_leaves = tuple(
            None if i in t_pos else _hashable(leaves[i])
            for i in range(len(leaves)))
        training = self._layer.training if self._layer is not None else None
        cache_key = (treedef, t_pos, static_leaves, training)

        if cache_key not in self._cache:
            layer = self._layer
            function = self._function
            raw_leaves = list(leaves)

            if layer is not None:
                params, others = _split_state(layer)

                @jax.jit
                def compiled(params, others, key, *datas):
                    new_leaves = list(raw_leaves)
                    for i, d in zip(t_pos, datas):
                        new_leaves[i] = Tensor(d)
                    a, k = jax.tree_util.tree_unflatten(treedef, new_leaves)
                    with key_stream(key):
                        out, buf = functional_call(layer, {**params, **others},
                                                   *a, return_buffers=True,
                                                   forward_fn=function, **k)
                    return out, buf

                if self._ir_passes:
                    compiled = self._wrap_ir(compiled)
                self._cache[cache_key] = ("layer", compiled)
            else:
                @jax.jit
                def compiled(key, *datas):
                    new_leaves = list(raw_leaves)
                    for i, d in zip(t_pos, datas):
                        new_leaves[i] = Tensor(d)
                    a, k = jax.tree_util.tree_unflatten(treedef, new_leaves)
                    with key_stream(key), mode.grad_enabled(False):
                        out = function(*a, **k)
                    return jax.tree_util.tree_map(
                        lambda t: t._data if isinstance(t, Tensor) else t, out,
                        is_leaf=_is_tensor)

                if self._ir_passes:
                    compiled = self._wrap_ir(compiled)
                self._cache[cache_key] = ("fn", compiled)

        kind, compiled = self._cache[cache_key]
        key = get_rng_key()
        if kind == "layer":
            params, others = _split_state(self._layer)
            out, buf = compiled(params, others, key, *datas)
            sd = self._layer.state_dict()
            for name, val in buf.items():
                if name in sd and sd[name].stop_gradient and \
                        not isinstance(val, jax.core.Tracer):
                    sd[name]._data = val
        else:
            out = compiled(key, *datas)
        return jax.tree_util.tree_map(
            lambda d: Tensor(d) if isinstance(d, jax.Array) else d, out)

    def _wrap_ir(self, compiled):
        """Re-jit the cached callable with the IR passes applied to its
        pure inner function (reference build_strategy fuse passes)."""
        from ..framework import ir

        inner = compiled.__wrapped__  # the function under @jax.jit
        passes = None if self._ir_passes is True else list(self._ir_passes)
        return jax.jit(ir.optimize(inner, passes=passes))

    @property
    def code(self):
        import inspect
        return inspect.getsource(self._function)


def _hashable(x):
    if isinstance(x, (list,)):
        return tuple(_hashable(i) for i in x)
    if isinstance(x, dict):
        return tuple(sorted((k, _hashable(v)) for k, v in x.items()))
    if isinstance(x, np.ndarray):
        return (x.shape, str(x.dtype), x.tobytes())
    return x


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """Compile a function or a Layer's forward (paddle.jit.to_static parity).

    Data-dependent python ``if``/``while`` on tensor values are converted
    by the dy2static AST pass (reference python/paddle/jit/dy2static/)
    into lax control flow; statements the pass can't convert keep the
    explicit trace-guard behavior, and any conversion failure falls back
    to plain tracing.

    ``ir_passes=True`` (or a sequence of pass names) runs the jaxpr
    pattern-rewrite passes (framework/ir.py) over the traced program —
    the reference's ``build_strategy`` fuse-pass role; a BuildStrategy
    object with any truthy ``fuse_*`` attribute enables them too.
    """
    import types

    from .dy2static import ast_transform

    ir_passes = kwargs.get("ir_passes")
    if ir_passes not in (None, True, False):
        # validate early: a bare string would iterate per-character and a
        # misspelled name would only KeyError deep inside the first trace
        from ..framework import ir as _ir
        if isinstance(ir_passes, str):
            raise TypeError(
                "ir_passes must be True/False or a SEQUENCE of pass "
                f"names, got the string {ir_passes!r} — did you mean "
                f"ir_passes=[{ir_passes!r}]?")
        unknown = [n for n in ir_passes if n not in _ir.PASSES]
        if unknown:
            raise ValueError(
                f"unknown ir pass(es) {unknown}; registered: "
                f"{list(_ir.PASSES)}")
    # explicit ir_passes=False is an OPT-OUT that build_strategy's fuse
    # flags must not override
    if "ir_passes" not in kwargs and build_strategy is not None:
        # only GRAPH-fusion BuildStrategy flags opt in — comm-fusion
        # flags (DistributedStrategy.fuse_all_reduce_ops etc.) are
        # semantically unrelated and default True
        _GRAPH_FUSE_FLAGS = ("fused_attention", "fuse_attention",
                             "fuse_elewise_add_act_ops",
                             "fuse_gemm_epilogue", "fuse_bn_act_ops",
                             "fuse_bn_add_act_ops",
                             "fuse_relu_depthwise_conv")
        ir_passes = any(bool(getattr(build_strategy, a, False))
                        for a in _GRAPH_FUSE_FLAGS)

    def decorate(fn):
        import inspect

        _gen_probe = fn.forward if isinstance(fn, Layer) else fn
        _gen_probe = getattr(_gen_probe, "__func__", _gen_probe)
        if inspect.isgeneratorfunction(_gen_probe) or \
                inspect.isasyncgenfunction(_gen_probe):
            # reference-quality decline: a compiled graph has one static
            # output structure; a generator's yields have none
            raise NotImplementedError(
                "to_static cannot compile a generator function: a "
                "jitted XLA program returns a fixed output structure, "
                "but `yield` produces values lazily. Restructure to "
                "accumulate results and return them (e.g. append to a "
                "list and return paddle.stack(outs)), or keep the "
                "generator outside the compiled region.")
        if isinstance(fn, Layer):
            raw = getattr(fn.forward, "__func__", fn.forward)
            conv = ast_transform(raw)
            fwd = types.MethodType(conv, fn) if conv is not None \
                else fn.forward
            static = StaticFunction(fwd, layer=fn, ir_passes=ir_passes)
            fn.forward = static
            return fn
        # a BOUND method must keep its binding through conversion: the
        # dy2static pass recompiles the underlying function, and calling
        # that unbound would swallow the first argument as self
        # (bug exposed by TranslatedLayer over Sequential, whose forward
        # has a convertible for-loop)
        self_obj = getattr(fn, "__self__", None)
        conv = ast_transform(getattr(fn, "__func__", fn))
        if conv is not None and self_obj is not None:
            conv = types.MethodType(conv, self_obj)
        return StaticFunction(conv if conv is not None else fn,
                              ir_passes=ir_passes)

    if function is not None:
        return decorate(function)
    return decorate


class TrainStep:
    """One whole training step compiled to a single XLA program.

    fwd + bwd (jax.grad over the functionalized model) + grad clip + optimizer
    update all fuse into one executable; parameters/optimizer state live on
    device across steps.  This is the TPU answer to the reference's fused
    optimizer kernels + CUDA-graph capture
    (paddle/phi/backends/gpu/cuda/cuda_graph.cc).

    Usage::
        step = TrainStep(model, loss_fn, opt)
        loss = step(batch_x, batch_y)      # Tensors in, loss Tensor out
    """

    def __init__(self, model, loss_fn, optimizer, donate=True, remat=False,
                 scaler=None):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.remat = remat
        self._params, self._frozen = _split_state(model)
        self._opt_state = optimizer.init_state_pytree(self._params)
        self._step = 0
        self._compiled = None
        self._donate = donate
        # loss scaling composed INTO the compiled step (reference
        # fleet/scaler.py distributed_scaler + update_loss_scaling_ kernel)
        self.scaler = scaler if (scaler is not None and scaler.is_enable()) \
            else None
        if self.scaler is not None:
            from ..amp import scaler_init_state
            self._scaler_state = scaler_init_state(self.scaler)
            self.scaler._compiled_state = self._scaler_state
        else:
            self._scaler_state = None

    def _build(self):
        model = self.model
        loss_fn = self.loss_fn
        optimizer = self.optimizer
        grad_clip = optimizer._grad_clip

        def make_loss_f(frozen, key, inputs, labels):
            def loss_f(p):
                with key_stream(key):
                    out = functional_call(model, {**p, **frozen}, *inputs)
                out_t = jax.tree_util.tree_map(
                    lambda d: Tensor(d) if isinstance(d, jax.Array) else d, out)
                label_t = tuple(Tensor(l) if isinstance(l, jax.Array) else l
                                for l in labels)
                with mode.grad_enabled(False):
                    loss = loss_fn(out_t, *label_t)
                return loss._data if isinstance(loss, Tensor) else loss

            if self.remat:
                # activation rematerialization: recompute the forward during
                # the backward pass instead of saving activations
                loss_f = jax.checkpoint(loss_f)
            return loss_f

        def step_fn(params, frozen, opt_state, step, lr, key, inputs, labels):
            loss_f = make_loss_f(frozen, key, inputs, labels)
            loss, grads = jax.value_and_grad(loss_f)(params)
            if grad_clip is not None:
                grads = grad_clip.clip_pytree(grads)
            new_params, new_opt = optimizer.apply_gradients_pytree(
                params, grads, opt_state, step, lr=lr)
            return loss, new_params, new_opt

        scaler = self.scaler

        def step_fn_scaled(params, frozen, opt_state, step, lr, key, inputs,
                           labels, scaler_state):
            from ..amp import scaler_guarded_update
            loss_f = make_loss_f(frozen, key, inputs, labels)

            def scaled_f(p):
                l = loss_f(p)
                return l * scaler_state["scale"].astype(l.dtype), l

            (_, loss), grads = jax.value_and_grad(
                scaled_f, has_aux=True)(params)
            new_params, new_opt, new_sstate = scaler_guarded_update(
                scaler, scaler_state, grads, grad_clip, optimizer,
                params, opt_state, step, lr)
            return loss, new_params, new_opt, new_sstate

        donate = (0, 2) if self._donate else ()
        self._compiled = jax.jit(
            step_fn_scaled if scaler is not None else step_fn,
            donate_argnums=donate)

    def __call__(self, inputs, labels=()):
        """inputs: Tensor or tuple for the model; labels: Tensor or tuple for
        loss_fn(output, *labels)."""
        if self._compiled is None:
            self._build()
        self._step += 1
        lr = jnp.float32(self.optimizer.get_lr())
        key = get_rng_key()
        if isinstance(inputs, Tensor):
            inputs = (inputs,)
        if isinstance(labels, Tensor):
            labels = (labels,)
        in_data = tuple(t._data if isinstance(t, Tensor) else t for t in inputs)
        lb_data = tuple(t._data if isinstance(t, Tensor) else t for t in labels)
        if self.scaler is not None:
            # the scaler object owns the live state (set_state_dict can
            # replace it between steps)
            loss, self._params, self._opt_state, new_sstate = \
                self._compiled(self._params, self._frozen, self._opt_state,
                               jnp.int32(self._step), lr, key, in_data,
                               lb_data, self.scaler._compiled_state)
            self.scaler._compiled_state = new_sstate
        else:
            loss, self._params, self._opt_state = self._compiled(
                self._params, self._frozen, self._opt_state,
                jnp.int32(self._step), lr, key, in_data, lb_data)
        self.sync_to_model()
        return Tensor(loss)

    def sync_to_model(self):
        """Rebind updated device arrays into the model's Parameters."""
        sd = self.model.state_dict()
        for name, arr in self._params.items():
            sd[name]._data = arr

    def state_dict(self):
        return {"params": self._params, "opt_state": self._opt_state,
                "step": self._step}


from .save_load import TranslatedLayer, load, save  # noqa: E402,F401
