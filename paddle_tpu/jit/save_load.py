"""paddle.jit.save / paddle.jit.load — TranslatedLayer parity.

Reference: jit.save serializes the traced program + params via
paddle/fluid/jit/serializer.cc and load returns a TranslatedLayer executing
it.  Here the Layer object (pure Python, Tensors pickle as host arrays) is
the program: save writes ``<prefix>.pdmodel`` (pickled structure) +
``<prefix>.pdiparams`` (state dict); load reconstructs the Layer and wraps
its forward in ``to_static`` so it executes as one compiled XLA program —
the same compiled-artifact semantics the reference gets from its serialized
ProgramDesc.
"""

import pickle

import numpy as np

from ..nn.layer_base import Layer

# Artifact format version (reference op_version_registry.h role).
# v1 = round-2 artifacts: bare pickled state dict, no wrapper.
# v2 wraps the params file in {"__format_version__", "state"}.
# Bump + register an upgrader in _STATE_UPGRADERS when the layout changes.
JIT_FORMAT_VERSION = 2

_STATE_UPGRADERS = {
    # v1 -> v2: same state-dict layout, only the wrapper is new
    1: lambda state: state,
}


class TranslatedLayer(Layer):
    """A loaded inference/training layer (reference
    python/paddle/jit/translated_layer.py)."""

    def __init__(self, inner):
        super().__init__()
        self._inner = inner
        from . import to_static
        self._compiled = to_static(inner.forward)

    def forward(self, *args, **kwargs):
        return self._compiled(*args, **kwargs)

    def state_dict(self, *a, **k):
        return self._inner.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._inner.set_state_dict(sd, *a, **k)

    def parameters(self, *a, **k):
        return self._inner.parameters(*a, **k)

    def train(self):
        self._inner.train()
        return super().train()

    def eval(self):
        self._inner.eval()
        return super().eval()


def save(layer, path, input_spec=None, **configs):
    """Save a Layer (or StaticFunction-decorated Layer) to ``path`` prefix."""
    from . import StaticFunction

    fwd = layer.forward
    restore = None
    if isinstance(fwd, StaticFunction):
        # unwrap the jit cache before pickling; re-wrapped on load
        restore = fwd
        layer.forward = fwd._function if hasattr(fwd, "_function") else \
            fwd.__wrapped__
    try:
        with open(path + ".pdmodel", "wb") as f:
            pickle.dump(layer, f)
    finally:
        if restore is not None:
            layer.forward = restore
    state = {k: np.asarray(v._data)
             for k, v in layer.state_dict().items()}
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump({"__format_version__": JIT_FORMAT_VERSION,
                     "state": state}, f)


def load(path, **configs):
    """Load a jit-saved model; returns a TranslatedLayer."""
    with open(path + ".pdmodel", "rb") as f:
        inner = pickle.load(f)
    try:
        with open(path + ".pdiparams", "rb") as f:
            state = pickle.load(f)
        if isinstance(state, dict) and "__format_version__" in state:
            version = int(state["__format_version__"])
            state = state["state"]
        else:
            version = 1  # round-2 artifact: bare state dict
        if version > JIT_FORMAT_VERSION:
            raise ValueError(
                f"{path}.pdiparams has format v{version}, newer than this "
                f"build's v{JIT_FORMAT_VERSION} — upgrade paddle_tpu")
        while version < JIT_FORMAT_VERSION:
            upgrader = _STATE_UPGRADERS.get(version)
            if upgrader is None:
                raise ValueError(
                    f"no upgrade path from jit.save format v{version}")
            state = upgrader(state)
            version += 1
        inner.set_state_dict(state)
    except FileNotFoundError:
        pass
    return TranslatedLayer(inner)
