"""dy2static — AST conversion of python control flow on tensor values.

Reference: python/paddle/jit/dy2static/ (20 AST transformers rewriting
``if``/``while``/``for`` into conditional_block/while ops via runtime
``convert_ifelse``/``convert_while_loop`` helpers).

TPU redesign keeps the reference's two-phase architecture but targets
lax: the AST pass rewrites ``if``/``while`` statements into calls to the
runtime converters below; the converters check the condition at RUN time
— a plain python value falls through to ordinary python control flow
(zero behavior change), a traced Tensor dispatches to
``static.nn.cond`` / ``while_loop`` so the branch compiles instead of
hitting the trace guard.

Rewrite shape (the reference's convert_ifelse pattern):

    if t.sum() > 0:          def __d2s_true_1(x, y):
        x = x + 1                x = x + 1
    else:                        return (x, y)
        y = x * 2     ==>    def __d2s_false_1(x, y):
                                 y = x * 2
                                 return (x, y)
                             (x, y) = __d2s_convert_ifelse(
                                 t.sum() > 0, __d2s_true_1, __d2s_false_1,
                                 (__d2s_get('x'), __d2s_get('y')))

Assigned names become branch-function parameters seeded from the call
site (``__d2s_get`` reads the caller's frame; missing names seed the
``_UNDEF`` sentinel so one-branch definitions still work on the python
path and raise a clear error if a compiled path leaves them unset).

Supported beyond plain if/while (reference loop_transformer.py,
return_transformer.py, break_continue_transformer.py semantics):

- ``return`` inside converted ``if`` blocks: early returns are
  canonicalized into if/else tail form (statements after a returning
  ``if`` move into its else-continuation), then both-return ifs lower
  to a value-returning ``lax.cond``.  A ``return`` whose branch only
  *sometimes* returns is left for the trace guard.
- ``return`` under a loop: rewritten into a carried (flag, value) pair
  + break, with a post-loop ``if flag: return value`` that the
  canonicalizer folds.  Exact on python-native loops; a
  tensor-converted loop raises an actionable error (the return value
  has no statically-shaped pre-loop form).  Returns under With/Try
  decline (unwind semantics).
- ``break``/``continue`` in ``while``/``for``: eliminated into flag
  variables + guard-ifs (the reference's break_continue_transformer
  rewrite); the loop test conjoins ``not brk``, so the flag rides the
  compiled ``lax.while_loop`` carry.
- ``for x in tensor``: lowered to an index-carried ``while_loop`` over
  the leading axis (python iterables keep the native loop); the loop
  variable's post-loop value is carried (python scoping parity).
- tuple for-targets (``for a, b in ...``, nesting included): the
  element names join the carried set and bind by unpacking each
  element; flat tuples also convert on the tensor path (seeded from the
  first row), nested patterns stay native-only.
- closures with free variables: the converted clone's code re-binds to
  the ORIGINAL cells, so nonlocal reads and writes stay live in both
  directions.

- ``while/for ... else``: converts — the else suite hoists after the
  loop, guarded on the carried break flag when a break exists (python
  semantics: else runs iff no break; exceeds the reference, whose
  loop_transformer has no orelse handling).
- ``yield``: a generator ENTRY POINT declines at decoration time with
  an actionable error (a compiled graph has one static output
  structure); generator helpers inside a compiled function run
  natively as iterables.

Conversion failure of any kind falls back to the original function.
"""

import ast
import functools
import inspect
import sys
import textwrap

__all__ = ["convert_ifelse", "convert_while", "convert_for",
           "convert_ifelse_ret", "ast_transform"]


class _Undefined:
    """Poison sentinel: ANY use raises, mirroring python's
    UnboundLocalError-on-read for a name assigned in an untaken branch."""

    __slots__ = ()

    def _explode(self, *a, **k):
        raise NameError(
            "variable assigned only inside an untaken to_static branch "
            "was used before assignment (dy2static)")

    __bool__ = __getattr__ = __call__ = __iter__ = __len__ = _explode
    __add__ = __radd__ = __sub__ = __rsub__ = __mul__ = __rmul__ = _explode
    __truediv__ = __rtruediv__ = __eq__ = __lt__ = __gt__ = _explode
    __getitem__ = __neg__ = __abs__ = _explode
    __repr__ = __str__ = __format__ = _explode  # no silent leak via print


_UNDEF = _Undefined()


def _noret():
    """Pre-loop seed for a loop-carried return value: the poison makes a
    traced-loop conversion fail with the actionable _undef_loop_msg
    instead of a shape error, and is never read on the python path."""
    return _UNDEF


def _select_outputs(fn, values, keep):
    out = fn(*values)
    seq = out if isinstance(out, (tuple, list)) else (out,)
    return tuple(o for i, o in enumerate(seq) if i in keep)


def _frame_get(name):
    """Call-site seed: the converted function's local, or _UNDEF."""
    frame = sys._getframe(1)
    return frame.f_locals.get(name, _UNDEF)


def _is_traced_bool(pred):
    import jax

    from ..core.tensor import Tensor

    data = pred._data if isinstance(pred, Tensor) else pred
    return isinstance(data, jax.core.Tracer)


def convert_ifelse(pred, true_fn, false_fn, both, values):
    """Runtime dispatch for a rewritten ``if``.

    Python bool → run ONE branch natively (exact eager semantics, tape
    autograd included; a name assigned only in the untaken branch binds
    the poison sentinel, which raises on first use — UnboundLocalError
    parity).

    Traced Tensor → both branches trace into lax.cond.  ``both`` marks
    (by position) names assigned in BOTH branches: those, plus names
    with a defined seed, are cond outputs; a name with an _UNDEF seed
    assigned in only one branch cannot cross lax.cond (the other path
    has no value of matching type) — it binds the poison instead, so
    dead branch-local temporaries are fine and a genuine read raises.
    """
    if not _is_traced_bool(pred):
        return true_fn(*values) if bool(pred) else false_fn(*values)
    from ..static import nn as static_nn

    keep = [i for i, v in enumerate(values)
            if i in both or v is not _UNDEF]
    keep_set = set(keep)
    outs = static_nn.cond(
        pred,
        lambda: _select_outputs(true_fn, values, keep_set),
        lambda: _select_outputs(false_fn, values, keep_set))
    outs = list(outs) if isinstance(outs, (tuple, list)) else [outs]
    full = []
    k = 0
    for i in range(len(values)):
        if i in keep_set:
            full.append(outs[k])
            k += 1
        else:
            full.append(_UNDEF)
    return tuple(full)


def convert_while(test_fn, body_fn, names, values):
    """Runtime dispatch for a rewritten ``while``.

    Python-bool tests loop natively; a traced test lowers to
    lax.while_loop (loop-invariant shapes required)."""
    first = test_fn(*values)
    if not _is_traced_bool(first):
        while bool(first):
            values = body_fn(*values)
            first = test_fn(*values)
        return tuple(values)
    from ..static import nn as static_nn

    values = _seed_inner_flags(names, values)
    for name, v in zip(names, values):
        if v is _UNDEF:
            raise NameError(_undef_loop_msg(name, "while"))
    return tuple(static_nn.while_loop(
        lambda *vs: test_fn(*vs), lambda *vs: tuple(body_fn(*vs)),
        list(values)))


def _seed_inner_flags(names, values):
    """A nested loop's break/continue flag is initialized INSIDE this
    loop's body (write-before-read by _rewrite_bc construction), so an
    _UNDEF pre-loop slot is dead — seed it False to keep the carry
    structure instead of raising the user-variable error."""
    return tuple(False if (v is _UNDEF
                           and (n.startswith("_d2s_brk")
                                or n.startswith("_d2s_cont")))
                 else v for n, v in zip(names, values))


def _undef_loop_msg(name, kind):
    if name.startswith("_d2s_retv"):
        return (
            f"`return` inside a tensor-converted {kind} loop cannot be "
            "compiled: the return value has no statically-shaped "
            "pre-loop form.  Assign a result variable in the loop and "
            "return it after the loop instead.")
    return (f"loop variable {name!r} is used in a compiled (tensor-"
            f"{'condition' if kind == 'while' else 'iterable'}) {kind} "
            "before assignment; initialize it before the loop")


def convert_ifelse_ret(pred, true_fn, false_fn, values):
    """Value-returning ``if``: both branches END in return (after
    canonicalization).  Python bool → one branch runs; traced → both
    trace into lax.cond, whose branches must return matching
    shapes/dtypes (lax raises a structure error otherwise — same
    restriction the reference places on static return_transformer
    outputs).  ``values`` seed the names assigned within the branches
    (reads of outer locals resolve by closure)."""
    if not _is_traced_bool(pred):
        return true_fn(*values) if bool(pred) else false_fn(*values)
    from ..static import nn as static_nn

    return static_nn.cond(pred, lambda: true_fn(*values),
                          lambda: false_fn(*values))


def _is_tensorish(v):
    import jax

    from ..core.tensor import Tensor

    return isinstance(v, (Tensor, jax.Array)) or \
        isinstance(v, jax.core.Tracer)


def d2s_not(v):
    """``not`` that stays traceable: logical_not for tensors."""
    if not _is_tensorish(v):
        return not v
    import jax.numpy as jnp

    from ..core.tensor import Tensor

    data = v._data if isinstance(v, Tensor) else v
    out = jnp.logical_not(data)
    return Tensor(out) if isinstance(v, Tensor) else out


def d2s_or(a, b):
    """Eager-argument logical or (flag combination — both args cheap)."""
    if not _is_tensorish(a) and not _is_tensorish(b):
        return a or b
    import jax.numpy as jnp

    from ..core.tensor import Tensor

    da = a._data if isinstance(a, Tensor) else a
    db = b._data if isinstance(b, Tensor) else b
    return Tensor(jnp.logical_or(da, db))


def d2s_and_lazy(a, b_thunk):
    """``a and b`` with python short-circuit preserved on the python
    path; tensor path evaluates both and combines (pure, so safe)."""
    if not _is_tensorish(a):
        return b_thunk() if bool(a) else False
    import jax.numpy as jnp

    from ..core.tensor import Tensor

    b = b_thunk()
    da = a._data if isinstance(a, Tensor) else a
    db = b._data if isinstance(b, Tensor) else b
    return Tensor(jnp.logical_and(da, db))


def convert_for(it, body_fn, names, values, brk_name=None, elt_spec=()):
    """Runtime dispatch for a rewritten ``for TARGET in it``.

    ``body_fn(x, *values) -> (x, *values)`` (the loop variable is carried
    so its post-loop value matches python scoping; tuple targets carry
    their element NAMES inside ``values`` and bind them by unpacking x at
    body start).  Python iterables run the native loop (honoring a break
    flag with a REAL break); tensor/array iterables lower to an
    index-carried while_loop over the leading axis — ragged early exit
    rides the ``brk`` flag in the test.  ``elt_spec`` maps flat tuple-
    target names to element positions so the traced path can seed their
    carried slots from the first row.  Returns ``(*values, x_last)``;
    ``x_last`` is ``_UNDEF`` for an empty python iterable (python's
    unbound-after-empty-loop parity).
    """
    brk_idx = names.index(brk_name) if brk_name else None
    if not _is_tensorish(it):
        x_last = _UNDEF
        for x in it:
            out = body_fn(x, *values)
            x_last, values = out[0], tuple(out[1:])
            if brk_idx is not None and bool(values[brk_idx]):
                break
        return (*values, x_last)

    from ..core.tensor import Tensor
    from ..static import nn as static_nn

    tens_seed = it if isinstance(it, Tensor) else Tensor(it)
    elt_names = {n for n, _ in elt_spec}
    values = list(values)
    if len(tens_seed.shape) and int(tens_seed.shape[0]) > 0:
        # seed element slots UNCONDITIONALLY: the unpack assign is the
        # first body statement, so any pre-loop value is dead — but a
        # differently-shaped one would poison the while carry structure
        # (review regression)
        for n, i in elt_spec:
            values[names.index(n)] = tens_seed[0][i]
    values = list(_seed_inner_flags(names, values))
    for name, v in zip(names, values):
        if v is _UNDEF and name not in elt_names:
            raise NameError(_undef_loop_msg(name, "for"))
    values = tuple(values)
    tens = tens_seed
    n = int(tens.shape[0])  # static leading axis (XLA requirement)
    if n == 0:
        return (*values, _UNDEF)

    import jax.numpy as jnp

    def test(i, x, *vals):
        ok = Tensor(jnp.asarray(True)) if brk_idx is None \
            else d2s_not(vals[brk_idx])
        return d2s_and_lazy(i < n, lambda: ok)

    def body(i, x, *vals):
        out = body_fn(tens[i], *vals)
        return (i + 1, out[0], *out[1:])

    i0 = Tensor(jnp.asarray(0, jnp.int32))
    out = static_nn.while_loop(test, body, [i0, tens[0], *values])
    return (*out[2:], out[1])


# ----------------------------------------------------- return canonical ----

class _Unsupported(Exception):
    """A return pattern the canonicalizer can't restructure — the caller
    skips return handling and leaves those ifs for the trace guard."""


class _ReturnFinder(ast.NodeVisitor):
    def __init__(self):
        self.found = False

    def visit_Return(self, node):
        self.found = True

    def visit_FunctionDef(self, node):
        pass

    visit_AsyncFunctionDef = visit_Lambda = visit_ClassDef = \
        visit_FunctionDef


def _contains_return(stmts):
    v = _ReturnFinder()
    for s in stmts:
        v.visit(s)
    return v.found


def _always_returns(stmts):
    """True when every path through ``stmts`` ends in a return."""
    if not stmts:
        return False
    last = stmts[-1]
    if isinstance(last, ast.Return):
        return True
    if isinstance(last, ast.If):
        return _always_returns(last.body) and _always_returns(last.orelse)
    return False


def _canonicalize_returns(stmts):
    """Restructure so every return is a trailing statement or inside an If
    both of whose branches always return (statements after a returning If
    fold into its continuation branch — the reference return_transformer's
    early-return elimination).  Raises _Unsupported for partial-return
    branches and returns under loops/try/with."""
    out = []
    for idx, s in enumerate(stmts):
        rest = stmts[idx + 1:]
        if isinstance(s, (ast.While, ast.For, ast.Try, ast.With)) \
                and _contains_return([s]):
            raise _Unsupported
        if isinstance(s, ast.If) and _contains_return([s]):
            b_ret = _contains_return(s.body)
            o_ret = _contains_return(s.orelse)
            if b_ret and not _always_returns(s.body):
                raise _Unsupported
            if o_ret and not _always_returns(s.orelse):
                raise _Unsupported
            if b_ret and o_ret:
                s.body = _canonicalize_returns(s.body)
                s.orelse = _canonicalize_returns(s.orelse)
                out.append(s)
                return out  # rest is unreachable
            if b_ret:
                s.body = _canonicalize_returns(s.body)
                s.orelse = _canonicalize_returns(list(s.orelse) + rest)
            else:
                s.orelse = _canonicalize_returns(s.orelse)
                s.body = _canonicalize_returns(list(s.body) + rest)
            out.append(s)
            return out
        out.append(s)
    return out


# ---------------------------------------------------- return under loop ----

def _returns_convertible(stmts):
    """Pre-scan: False when any this-level return sits under With/Try
    (unwind semantics the flag rewrite can't model).  MUST run before
    any mutation — a partial rewrite that then declines would leave a
    return silently turned into a bare break (review regression)."""
    for s in stmts:
        if isinstance(s, (ast.With, ast.Try)) and _contains_return([s]):
            return False
        if isinstance(s, ast.If):
            if not _returns_convertible(s.body) or \
                    not _returns_convertible(s.orelse):
                return False
    return True


def _replace_returns(stmts, flag, val):
    """Rewrite this-level ``return X`` into ``val = X; flag = True;
    break`` (the break rides the existing flag machinery).  Recurses into
    If branches only — nested loops were already cleansed by the
    post-order visit, and nested defs keep their own returns.  Callers
    gate on :func:`_returns_convertible` first."""
    out = []
    for s in stmts:
        if isinstance(s, ast.Return):
            v = s.value if s.value is not None else ast.Constant(value=None)
            out.append(ast.Assign(
                targets=[ast.Name(id=val, ctx=ast.Store())], value=v))
            out.append(_assign_flag(flag, True))
            out.append(ast.Break())
            break  # anything after a return is unreachable
        if isinstance(s, ast.If):
            s.body = _replace_returns(s.body, flag, val)
            s.orelse = _replace_returns(s.orelse, flag, val)
        out.append(s)
    return out


class _ReturnInLoopTransformer(ast.NodeTransformer):
    """``return`` under a loop -> carried (flag, value) + break + a
    post-loop ``if flag: return value`` that the canonicalizer then
    folds (the reference return_transformer's loop case).  Post-order,
    so inner loops hand their returns outward level by level."""

    def __init__(self):
        self.counter = 0
        self.changed = False

    def _handle(self, node):
        self.generic_visit(node)
        if node.orelse or not _contains_return(node.body):
            return node
        if not _returns_convertible(node.body):
            return node
        self.counter += 1
        flag = f"_d2s_retf{self.counter}"
        val = f"_d2s_retv{self.counter}"
        node.body = _replace_returns(node.body, flag, val)
        self.changed = True
        return [
            _assign_flag(flag, False),
            ast.Assign(targets=[ast.Name(id=val, ctx=ast.Store())],
                       value=ast.Call(
                           func=ast.Name(id="__d2s_noret", ctx=ast.Load()),
                           args=[], keywords=[])),
            node,
            ast.If(test=ast.Name(id=flag, ctx=ast.Load()),
                   body=[ast.Return(value=ast.Name(id=val,
                                                   ctx=ast.Load()))],
                   orelse=[]),
        ]

    visit_For = visit_While = _handle
    # nested defs are visited too: each def's loop-returns resolve to a
    # post-loop if-return INSIDE that def — independent and correct


# ------------------------------------------------- break/continue flags ----

class _BreakContinueFinder(ast.NodeVisitor):
    """break/continue belonging to THIS loop level (not nested loops)."""

    def __init__(self):
        self.has_break = False
        self.has_continue = False

    def visit_Break(self, node):
        self.has_break = True

    def visit_Continue(self, node):
        self.has_continue = True

    def visit_While(self, node):
        pass

    visit_For = visit_FunctionDef = visit_AsyncFunctionDef = visit_While
    visit_Lambda = visit_ClassDef = visit_While


def _find_bc(stmts):
    v = _BreakContinueFinder()
    for s in stmts:
        v.visit(s)
    return v.has_break, v.has_continue


def _assign_flag(name, value):
    return ast.Assign(targets=[ast.Name(id=name, ctx=ast.Store())],
                      value=ast.Constant(value=value))


def _flag_expr(brk, cont):
    names = [n for n in (brk, cont) if n]
    if len(names) == 1:
        return ast.Name(id=names[0], ctx=ast.Load())
    return ast.Call(func=ast.Name(id="__d2s_or", ctx=ast.Load()),
                    args=[ast.Name(id=names[0], ctx=ast.Load()),
                          ast.Name(id=names[1], ctx=ast.Load())],
                    keywords=[])


def _guard_rewrite(stmts, brk, cont):
    """Replace this-level break/continue with flag sets and wrap every
    statement suffix following a may-escape statement in
    ``if not (brk or cont):`` (the reference break_continue_transformer
    rewrite, targeting tensor-traceable guard ifs)."""
    out = []
    for i, s in enumerate(stmts):
        b, c = _find_bc([s])
        if isinstance(s, ast.Break):
            out.append(_assign_flag(brk, True))
        elif isinstance(s, ast.Continue):
            out.append(_assign_flag(cont, True))
        elif isinstance(s, ast.If) and (b or c):
            s.body = _guard_rewrite(s.body, brk, cont)
            s.orelse = _guard_rewrite(s.orelse, brk, cont)
            out.append(s)
        else:
            out.append(s)
            continue
        rest = _guard_rewrite(stmts[i + 1:], brk, cont)
        if rest:
            guard = ast.If(
                test=ast.Call(func=ast.Name(id="__d2s_not", ctx=ast.Load()),
                              args=[_flag_expr(brk if b else None,
                                               cont if c else None)
                                    if (b != c) else _flag_expr(brk, cont)],
                              keywords=[]),
                body=rest, orelse=[])
            out.append(guard)
        return out
    return out


class _LoopEscapeTransformer(ast.NodeTransformer):
    """Eliminate break/continue into carried flag variables (post-order:
    innermost loops first).  Flags are named ``_d2s_*`` (single
    underscore) so the control-flow transformer carries them through
    cond/while outputs like user variables."""

    def __init__(self):
        self.counter = 0
        self.changed = False

    def _fresh(self, hint):
        self.counter += 1
        return f"_d2s_{hint}{self.counter}"

    def _declines(self, node, is_for):
        """Decline cases shared by b/c elimination and else-hoisting:
        a loop the control-flow transformer will NOT convert must keep
        its native form."""
        if _has_escape_sans_bc(node.body):
            return True
        if is_for and not _for_target_names(node.target):
            return True
        if not is_for and any(isinstance(n, ast.NamedExpr)
                              for n in ast.walk(node.test)):
            return True
        return False

    def _handle_loop(self, node, is_for):
        self.generic_visit(node)
        has_b, has_c = _find_bc(node.body)
        if node.orelse:
            # python loop-else: the else suite runs iff the loop exits
            # WITHOUT break.  No break -> hoist it after the loop
            # unconditionally; with break -> guard it on the carried
            # flag.  (The reference's loop_transformer has no orelse
            # handling at all — this exceeds it.)
            if self._declines(node, is_for) or _has_escape(node.orelse):
                return node
            orelse = list(node.orelse)
            node.orelse = []
            self.changed = True
            out = self._rewrite_bc(node, is_for, has_b, has_c)
            if has_b:
                guard = ast.If(
                    test=ast.Call(
                        func=ast.Name(id="__d2s_not", ctx=ast.Load()),
                        args=[ast.Name(id=node._d2s_brk,
                                       ctx=ast.Load())],
                        keywords=[]),
                    body=orelse, orelse=[])
                return out + [guard]
            return out + orelse
        if not (has_b or has_c):
            return node
        # Only rewrite loops the control-flow transformer WILL convert;
        # a declined loop (tuple for-target, other escapes in body) must
        # keep its real break/continue for native semantics.
        if self._declines(node, is_for):
            return node
        return self._rewrite_bc(node, is_for, has_b, has_c)

    def _rewrite_bc(self, node, is_for, has_b, has_c):
        """Eliminate break/continue into carried flags; returns the
        statement list replacing the loop ([flag inits..., loop])."""
        if not (has_b or has_c):
            return [node]
        brk = self._fresh("brk") if has_b else None
        cont = self._fresh("cont") if has_c else None
        node._d2s_brk = brk  # this loop's OWN flag (nested loops get
        # their own; name scanning would confuse them)
        body = _guard_rewrite(node.body, brk, cont)
        if cont:
            body = [_assign_flag(cont, False)] + body
        node.body = body
        pre = []
        if brk:
            pre.append(_assign_flag(brk, False))
            if not is_for:
                # while test := (not brk) and (test); lazy on python path
                node.test = ast.Call(
                    func=ast.Name(id="__d2s_and", ctx=ast.Load()),
                    args=[ast.Call(func=ast.Name(id="__d2s_not",
                                                 ctx=ast.Load()),
                                   args=[ast.Name(id=brk, ctx=ast.Load())],
                                   keywords=[]),
                          ast.Lambda(args=_args([]), body=node.test)],
                    keywords=[])
        if cont:
            pre.append(_assign_flag(cont, False))
        self.changed = True
        return pre + [node]

    def visit_While(self, node):
        return self._handle_loop(node, is_for=False)

    def visit_For(self, node):
        return self._handle_loop(node, is_for=True)


# ------------------------------------------------------------- AST pass ----

class _AssignedNames(ast.NodeVisitor):
    """Names bound by assignments in a statement list (no nested defs)."""

    def __init__(self):
        self.names = []

    def _add(self, name):
        if name not in self.names:
            self.names.append(name)

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Store):
            self._add(node.id)

    def visit_ListComp(self, node):
        pass  # comprehension targets live in their own scope

    visit_SetComp = visit_DictComp = visit_GeneratorExp = visit_ListComp

    def visit_Import(self, node):
        for a in node.names:
            self._add(a.asname or a.name.split(".")[0])

    def visit_ImportFrom(self, node):
        for a in node.names:
            self._add(a.asname or a.name)

    def visit_FunctionDef(self, node):
        self._add(node.name)

    def visit_AsyncFunctionDef(self, node):
        self._add(node.name)

    def visit_Lambda(self, node):
        pass

    def visit_ClassDef(self, node):
        self._add(node.name)


def _assigned(stmts):
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    return v.names


class _HasEscape(ast.NodeVisitor):
    """return/yield anywhere, break/continue not enclosed in a nested
    loop, nonlocal/global declarations (param-passing would break them)."""

    def __init__(self):
        self.found = False
        self._loop_depth = 0

    def visit_Return(self, node):
        self.found = True

    def visit_Raise(self, node):
        # both branches trace under lax.cond: a conditional raise would
        # fire unconditionally at trace time
        self.found = True

    def visit_Yield(self, node):
        self.found = True

    def visit_YieldFrom(self, node):
        self.found = True

    def visit_Nonlocal(self, node):
        self.found = True

    def visit_Global(self, node):
        self.found = True

    def visit_Delete(self, node):
        self.found = True  # del unbinds: param-passing can't model it

    def visit_ExceptHandler(self, node):
        if node.name:  # `except E as e`: e is unbound after the block
            self.found = True
        self.generic_visit(node)

    def visit_Break(self, node):
        if self._loop_depth == 0:
            self.found = True

    def visit_Continue(self, node):
        if self._loop_depth == 0:
            self.found = True

    def _loop(self, node):
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_While = _loop
    visit_For = _loop

    def visit_FunctionDef(self, node):
        pass

    def visit_AsyncFunctionDef(self, node):
        pass

    def visit_Lambda(self, node):
        pass


def _has_escape(stmts):
    v = _HasEscape()
    for s in stmts:
        v.visit(s)
    return v.found


def _has_escape_sans_return(stmts):
    """Escapes OTHER than return (yield/raise/nonlocal/del/...) — used for
    canonical both-return ifs, where returns are the expected exit."""
    v = _HasEscape()
    v.visit_Return = lambda node: None
    for s in stmts:
        v.visit(s)
    return v.found


def _has_escape_sans_bc(stmts):
    """Escapes other than this-level break/continue — the pre-check before
    the flag rewrite: a loop the control-flow transformer would decline
    anyway (return/yield/raise/... in body) must KEEP its real break, or
    the flag-only form silently changes native-loop semantics."""
    v = _HasEscape()
    v.visit_Break = lambda node: None
    v.visit_Continue = lambda node: None
    for s in stmts:
        v.visit(s)
    return v.found


def _for_target_names(target):
    """Names bound by a for target: a Name, or a (possibly nested) tuple
    of Names; None for anything else (starred/attribute/subscript)."""
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out = []
        for e in target.elts:
            sub = _for_target_names(e)
            if sub is None:
                return None
            out.extend(sub)
        return out
    return None


def _args(names):
    return ast.arguments(posonlyargs=[], args=[ast.arg(arg=n)
                                               for n in names],
                         kwonlyargs=[], kw_defaults=[], defaults=[])


def _seed_tuple(names):
    return ast.Tuple(elts=[ast.Call(
        func=ast.Name(id="__d2s_get", ctx=ast.Load()),
        args=[ast.Constant(value=n)], keywords=[]) for n in names],
        ctx=ast.Load())


def _ret_tuple(names):
    return ast.Return(value=ast.Tuple(
        elts=[ast.Name(id=n, ctx=ast.Load()) for n in names],
        ctx=ast.Load()))


def _bind_target(names):
    # always a tuple target — the branch/body fns return tuples even for
    # one name, so `(y,) = call` unpacks consistently
    return ast.Tuple(elts=[ast.Name(id=n, ctx=ast.Store())
                           for n in names], ctx=ast.Store())


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self.counter = 0

    def _fresh(self, hint):
        self.counter += 1
        return f"__d2s_{hint}_{self.counter}"

    def visit_If(self, node):
        self.generic_visit(node)
        if (_contains_return(node.body) or _contains_return(node.orelse)):
            if _always_returns(node.body) and _always_returns(node.orelse) \
                    and not (_has_escape_sans_return(node.body)
                             or _has_escape_sans_return(node.orelse)):
                return self._ret_if(node)
            return node
        if _has_escape(node.body) or _has_escape(node.orelse):
            return node
        body_names = [n for n in _assigned(node.body)
                      if not n.startswith("__d2s")]
        orelse_names = [n for n in _assigned(node.orelse)
                        if not n.startswith("__d2s")]
        names = sorted(set(body_names) | set(orelse_names))
        both = [i for i, n in enumerate(names)
                if n in body_names and n in orelse_names]

        true_name = self._fresh("true")
        false_name = self._fresh("false")
        body = list(node.body) + ([_ret_tuple(names)] if names
                                  else [ast.Return(value=ast.Constant(
                                      value=None))])
        orelse = (list(node.orelse) or [ast.Pass()]) + \
            ([_ret_tuple(names)] if names
             else [ast.Return(value=ast.Constant(value=None))])
        true_def = ast.FunctionDef(name=true_name, args=_args(names),
                                   body=body, decorator_list=[])
        false_def = ast.FunctionDef(name=false_name, args=_args(names),
                                    body=orelse, decorator_list=[])
        call = ast.Call(
            func=ast.Name(id="__d2s_convert_ifelse", ctx=ast.Load()),
            args=[node.test,
                  ast.Name(id=true_name, ctx=ast.Load()),
                  ast.Name(id=false_name, ctx=ast.Load()),
                  ast.Call(func=ast.Name(id="frozenset", ctx=ast.Load()),
                           args=[ast.Tuple(
                               elts=[ast.Constant(value=i) for i in both],
                               ctx=ast.Load())], keywords=[]),
                  _seed_tuple(names)],
            keywords=[])
        stmt = (ast.Assign(targets=[_bind_target(names)], value=call)
                if names else ast.Expr(value=call))
        return [true_def, false_def, stmt]

    def _ret_if(self, node):
        """Both branches end in return (canonical form): lower to a
        value-returning convert_ifelse_ret and RETURN its result."""
        names = sorted(set(
            n for n in _assigned(node.body) + _assigned(node.orelse)
            if not n.startswith("__d2s")))
        true_name = self._fresh("rtrue")
        false_name = self._fresh("rfalse")
        true_def = ast.FunctionDef(name=true_name, args=_args(names),
                                   body=list(node.body), decorator_list=[])
        false_def = ast.FunctionDef(name=false_name, args=_args(names),
                                    body=list(node.orelse),
                                    decorator_list=[])
        call = ast.Call(
            func=ast.Name(id="__d2s_convert_ifelse_ret", ctx=ast.Load()),
            args=[node.test,
                  ast.Name(id=true_name, ctx=ast.Load()),
                  ast.Name(id=false_name, ctx=ast.Load()),
                  _seed_tuple(names)],
            keywords=[])
        self.counter += 1
        return [true_def, false_def, ast.Return(value=call)]

    def visit_For(self, node):
        self.generic_visit(node)
        if node.orelse or _has_escape(node.body):
            return node
        tnames = _for_target_names(node.target)
        if tnames is None:
            return node  # starred/attribute targets: can't be carried
        is_tuple = not isinstance(node.target, ast.Name)
        if is_tuple:
            # element names join the carried set: python scoping
            # (rebinding, post-loop values, unbound-after-empty) falls
            # out of the ordinary carry rules
            names = sorted(n for n in
                           set(_assigned(node.body)) | set(tnames)
                           if not n.startswith("__d2s"))
            target_carry = self._fresh("xlast")  # raw element, discarded
        else:
            names = sorted(n for n in set(_assigned(node.body))
                           if not n.startswith("__d2s")
                           and n != node.target.id)
            target_carry = node.target.id
        # flat (name, position) pairs let the traced path seed elements
        # from the first row; nested patterns stay native-only
        elt_spec = []
        if is_tuple and all(isinstance(e, ast.Name)
                            for e in node.target.elts):
            elt_spec = [(e.id, i) for i, e in enumerate(node.target.elts)]
        brk_name = getattr(node, "_d2s_brk", None)
        if brk_name is not None and brk_name not in names:
            brk_name = None  # defensive: flag must be carried to matter
        body_name = self._fresh("forbody")
        x_arg = "__d2s_x"
        # the element binds through the ORIGINAL target node (a tuple
        # target unpacks naturally)
        body = [ast.Assign(targets=[node.target],
                           value=ast.Name(id=x_arg, ctx=ast.Load()))] \
            + list(node.body) \
            + [_ret_tuple([x_arg if is_tuple else target_carry] + names)]
        body_def = ast.FunctionDef(name=body_name,
                                   args=_args([x_arg] + names),
                                   body=body, decorator_list=[])
        call = ast.Call(
            func=ast.Name(id="__d2s_convert_for", ctx=ast.Load()),
            args=[node.iter,
                  ast.Name(id=body_name, ctx=ast.Load()),
                  ast.Tuple(elts=[ast.Constant(value=n) for n in names],
                            ctx=ast.Load()),
                  _seed_tuple(names),
                  ast.Constant(value=brk_name),
                  ast.Tuple(elts=[
                      ast.Tuple(elts=[ast.Constant(value=n),
                                      ast.Constant(value=i)],
                                ctx=ast.Load())
                      for n, i in elt_spec], ctx=ast.Load())],
            keywords=[])
        assign = ast.Assign(targets=[_bind_target(names + [target_carry])],
                            value=call)
        self.counter += 1
        return [body_def, assign]

    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse or _has_escape(node.body):
            return node
        if any(isinstance(n, ast.NamedExpr) for n in ast.walk(node.test)):
            return node  # walrus binds inside the nested test fn
        names = sorted(n for n in set(_assigned(node.body))
                       if not n.startswith("__d2s"))
        if not names:
            return node

        test_name = self._fresh("test")
        body_name = self._fresh("body")
        test_def = ast.FunctionDef(
            name=test_name, args=_args(names),
            body=[ast.Return(value=node.test)], decorator_list=[])
        body_def = ast.FunctionDef(
            name=body_name, args=_args(names),
            body=list(node.body) + [_ret_tuple(names)], decorator_list=[])
        call = ast.Call(
            func=ast.Name(id="__d2s_convert_while", ctx=ast.Load()),
            args=[ast.Name(id=test_name, ctx=ast.Load()),
                  ast.Name(id=body_name, ctx=ast.Load()),
                  ast.Tuple(elts=[ast.Constant(value=n) for n in names],
                            ctx=ast.Load()),
                  _seed_tuple(names)],
            keywords=[])
        assign = ast.Assign(targets=[_bind_target(names)], value=call)
        return [test_def, body_def, assign]


def ast_transform(fn):
    """Control-flow-converted clone of ``fn``, or None when conversion
    isn't possible (no source, nothing to convert, exec failure).
    Identical behavior for python-bool conditions.  Closures convert via
    an outer wrapper whose compiled code is re-bound to the ORIGINAL
    cells, so nonlocal reads/writes stay live."""
    closure_cells = getattr(fn, "__closure__", None) or ()
    freevars = fn.__code__.co_freevars if closure_cells else ()
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return None
    fdef = tree.body[0]
    if not isinstance(fdef, ast.FunctionDef):
        return None
    fdef.decorator_list = []  # the caller re-wraps

    def _mangled(name):
        return name.startswith("__") and not name.endswith("__")

    for n in ast.walk(fdef):
        # private-name mangling (self.__x -> _Cls__x) happens at class
        # compile time; re-exec at module scope loses it — fall back
        if isinstance(n, ast.Attribute) and _mangled(n.attr):
            return None
        if isinstance(n, ast.Name) and _mangled(n.id):
            return None

    # 0) returns under loops -> carried (flag, value) + break + a
    #    post-loop if-return (feeds the canonicalizer below)
    ret_loop = _ReturnInLoopTransformer()
    tree = ret_loop.visit(tree)
    fdef = tree.body[0]

    # 1) early-return canonicalization (best-effort: unsupported patterns
    #    keep their returns, and the If transformer leaves those alone)
    if any(isinstance(s, ast.If) and _contains_return([s])
           for s in ast.walk(fdef)):
        try:
            body = list(fdef.body)
            if not _always_returns(body):
                body = body + [ast.Return(value=ast.Constant(value=None))]
            fdef.body = _canonicalize_returns(body)
        except _Unsupported:
            pass

    # 2) break/continue -> carried flags + guard ifs
    escape = _LoopEscapeTransformer()
    tree = escape.visit(tree)

    # 3) if/while/for -> runtime converter calls
    transformer = _ControlFlowTransformer()
    new_tree = transformer.visit(tree)
    if transformer.counter == 0 and not escape.changed \
            and not ret_loop.changed:
        return None
    if freevars:
        # compile the converted def inside a wrapper that declares the
        # free names, so the inner code object carries real freevars
        fdef = new_tree.body[0]
        outer = ast.FunctionDef(
            name="__d2s_outer", args=_args(list(freevars)),
            body=[fdef, ast.Return(value=ast.Name(id=fdef.name,
                                                  ctx=ast.Load()))],
            decorator_list=[])
        new_tree = ast.Module(body=[outer], type_ignores=[])
    ast.fix_missing_locations(new_tree)

    try:
        code = compile(new_tree, filename=f"<dy2static {fn.__name__}>",
                       mode="exec")
    except (SyntaxError, ValueError):
        return None
    # exec against the LIVE module globals so helpers defined after
    # decoration (or monkeypatched later) resolve exactly as they would
    # in the original function; only prefixed helper names are injected
    glb = fn.__globals__
    glb["__d2s_convert_ifelse"] = convert_ifelse
    glb["__d2s_convert_while"] = convert_while
    glb["__d2s_convert_for"] = convert_for
    glb["__d2s_convert_ifelse_ret"] = convert_ifelse_ret
    glb["__d2s_not"] = d2s_not
    glb["__d2s_or"] = d2s_or
    glb["__d2s_and"] = d2s_and_lazy
    glb["__d2s_get"] = _frame_get
    glb["__d2s_noret"] = _noret
    loc = {}
    try:
        exec(code, glb, loc)
    except Exception:
        return None
    if freevars:
        import types

        outer_fn = loc.get("__d2s_outer") or glb.get("__d2s_outer")
        if outer_fn is None:
            return None
        try:
            # call with the LIVE contents to materialize the inner code
            # object, then re-bind it to the ORIGINAL cells by name so
            # later nonlocal mutations stay visible both ways
            inner = outer_fn(*[c.cell_contents for c in closure_cells])
            cellmap = dict(zip(fn.__code__.co_freevars, closure_cells))
            cells = tuple(cellmap[n] for n in inner.__code__.co_freevars)
            converted = types.FunctionType(
                inner.__code__, glb, fdef.name, fn.__defaults__, cells)
        except (ValueError, KeyError):
            return None  # empty cell / freevar mismatch: decline
    else:
        converted = loc.get(fdef.name) or glb.get(fdef.name)
    if converted is None:
        return None
    converted.__defaults__ = fn.__defaults__
    if fn.__kwdefaults__:
        converted.__kwdefaults__ = dict(fn.__kwdefaults__)
    return functools.wraps(fn)(converted)
