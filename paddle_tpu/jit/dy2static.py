"""dy2static — AST conversion of python control flow on tensor values.

Reference: python/paddle/jit/dy2static/ (20 AST transformers rewriting
``if``/``while``/``for`` into conditional_block/while ops via runtime
``convert_ifelse``/``convert_while_loop`` helpers).

TPU redesign keeps the reference's two-phase architecture but targets
lax: the AST pass rewrites ``if``/``while`` statements into calls to the
runtime converters below; the converters check the condition at RUN time
— a plain python value falls through to ordinary python control flow
(zero behavior change), a traced Tensor dispatches to
``static.nn.cond`` / ``while_loop`` so the branch compiles instead of
hitting the trace guard.

Rewrite shape (the reference's convert_ifelse pattern):

    if t.sum() > 0:          def __d2s_true_1(x, y):
        x = x + 1                x = x + 1
    else:                        return (x, y)
        y = x * 2     ==>    def __d2s_false_1(x, y):
                                 y = x * 2
                                 return (x, y)
                             (x, y) = __d2s_convert_ifelse(
                                 t.sum() > 0, __d2s_true_1, __d2s_false_1,
                                 (__d2s_get('x'), __d2s_get('y')))

Assigned names become branch-function parameters seeded from the call
site (``__d2s_get`` reads the caller's frame; missing names seed the
``_UNDEF`` sentinel so one-branch definitions still work on the python
path and raise a clear error if a compiled path leaves them unset).

Out of scope (left untransformed; the trace guard reports them if a
tensor condition reaches one): ``return``/``break``/``continue``/
``yield`` inside the converted block, ``while ... else``, closures with
free variables.  Conversion failure of any kind falls back to the
original function.
"""

import ast
import functools
import inspect
import sys
import textwrap

__all__ = ["convert_ifelse", "convert_while", "ast_transform"]


class _Undefined:
    """Poison sentinel: ANY use raises, mirroring python's
    UnboundLocalError-on-read for a name assigned in an untaken branch."""

    __slots__ = ()

    def _explode(self, *a, **k):
        raise NameError(
            "variable assigned only inside an untaken to_static branch "
            "was used before assignment (dy2static)")

    __bool__ = __getattr__ = __call__ = __iter__ = __len__ = _explode
    __add__ = __radd__ = __sub__ = __rsub__ = __mul__ = __rmul__ = _explode
    __truediv__ = __rtruediv__ = __eq__ = __lt__ = __gt__ = _explode
    __getitem__ = __neg__ = __abs__ = _explode
    __repr__ = __str__ = __format__ = _explode  # no silent leak via print


_UNDEF = _Undefined()


def _select_outputs(fn, values, keep):
    out = fn(*values)
    seq = out if isinstance(out, (tuple, list)) else (out,)
    return tuple(o for i, o in enumerate(seq) if i in keep)


def _frame_get(name):
    """Call-site seed: the converted function's local, or _UNDEF."""
    frame = sys._getframe(1)
    return frame.f_locals.get(name, _UNDEF)


def _is_traced_bool(pred):
    import jax

    from ..core.tensor import Tensor

    data = pred._data if isinstance(pred, Tensor) else pred
    return isinstance(data, jax.core.Tracer)


def convert_ifelse(pred, true_fn, false_fn, both, values):
    """Runtime dispatch for a rewritten ``if``.

    Python bool → run ONE branch natively (exact eager semantics, tape
    autograd included; a name assigned only in the untaken branch binds
    the poison sentinel, which raises on first use — UnboundLocalError
    parity).

    Traced Tensor → both branches trace into lax.cond.  ``both`` marks
    (by position) names assigned in BOTH branches: those, plus names
    with a defined seed, are cond outputs; a name with an _UNDEF seed
    assigned in only one branch cannot cross lax.cond (the other path
    has no value of matching type) — it binds the poison instead, so
    dead branch-local temporaries are fine and a genuine read raises.
    """
    if not _is_traced_bool(pred):
        return true_fn(*values) if bool(pred) else false_fn(*values)
    from ..static import nn as static_nn

    keep = [i for i, v in enumerate(values)
            if i in both or v is not _UNDEF]
    keep_set = set(keep)
    outs = static_nn.cond(
        pred,
        lambda: _select_outputs(true_fn, values, keep_set),
        lambda: _select_outputs(false_fn, values, keep_set))
    outs = list(outs) if isinstance(outs, (tuple, list)) else [outs]
    full = []
    k = 0
    for i in range(len(values)):
        if i in keep_set:
            full.append(outs[k])
            k += 1
        else:
            full.append(_UNDEF)
    return tuple(full)


def convert_while(test_fn, body_fn, names, values):
    """Runtime dispatch for a rewritten ``while``.

    Python-bool tests loop natively; a traced test lowers to
    lax.while_loop (loop-invariant shapes required)."""
    first = test_fn(*values)
    if not _is_traced_bool(first):
        while bool(first):
            values = body_fn(*values)
            first = test_fn(*values)
        return tuple(values)
    from ..static import nn as static_nn

    for name, v in zip(names, values):
        if v is _UNDEF:
            raise NameError(
                f"loop variable {name!r} is used in a compiled (tensor-"
                "condition) while before assignment; initialize it before "
                "the loop")
    return tuple(static_nn.while_loop(
        lambda *vs: test_fn(*vs), lambda *vs: tuple(body_fn(*vs)),
        list(values)))


# ------------------------------------------------------------- AST pass ----

class _AssignedNames(ast.NodeVisitor):
    """Names bound by assignments in a statement list (no nested defs)."""

    def __init__(self):
        self.names = []

    def _add(self, name):
        if name not in self.names:
            self.names.append(name)

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Store):
            self._add(node.id)

    def visit_ListComp(self, node):
        pass  # comprehension targets live in their own scope

    visit_SetComp = visit_DictComp = visit_GeneratorExp = visit_ListComp

    def visit_Import(self, node):
        for a in node.names:
            self._add(a.asname or a.name.split(".")[0])

    def visit_ImportFrom(self, node):
        for a in node.names:
            self._add(a.asname or a.name)

    def visit_FunctionDef(self, node):
        self._add(node.name)

    def visit_AsyncFunctionDef(self, node):
        self._add(node.name)

    def visit_Lambda(self, node):
        pass

    def visit_ClassDef(self, node):
        self._add(node.name)


def _assigned(stmts):
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    return v.names


class _HasEscape(ast.NodeVisitor):
    """return/yield anywhere, break/continue not enclosed in a nested
    loop, nonlocal/global declarations (param-passing would break them)."""

    def __init__(self):
        self.found = False
        self._loop_depth = 0

    def visit_Return(self, node):
        self.found = True

    def visit_Raise(self, node):
        # both branches trace under lax.cond: a conditional raise would
        # fire unconditionally at trace time
        self.found = True

    def visit_Yield(self, node):
        self.found = True

    def visit_YieldFrom(self, node):
        self.found = True

    def visit_Nonlocal(self, node):
        self.found = True

    def visit_Global(self, node):
        self.found = True

    def visit_Delete(self, node):
        self.found = True  # del unbinds: param-passing can't model it

    def visit_ExceptHandler(self, node):
        if node.name:  # `except E as e`: e is unbound after the block
            self.found = True
        self.generic_visit(node)

    def visit_Break(self, node):
        if self._loop_depth == 0:
            self.found = True

    def visit_Continue(self, node):
        if self._loop_depth == 0:
            self.found = True

    def _loop(self, node):
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_While = _loop
    visit_For = _loop

    def visit_FunctionDef(self, node):
        pass

    def visit_AsyncFunctionDef(self, node):
        pass

    def visit_Lambda(self, node):
        pass


def _has_escape(stmts):
    v = _HasEscape()
    for s in stmts:
        v.visit(s)
    return v.found


def _args(names):
    return ast.arguments(posonlyargs=[], args=[ast.arg(arg=n)
                                               for n in names],
                         kwonlyargs=[], kw_defaults=[], defaults=[])


def _seed_tuple(names):
    return ast.Tuple(elts=[ast.Call(
        func=ast.Name(id="__d2s_get", ctx=ast.Load()),
        args=[ast.Constant(value=n)], keywords=[]) for n in names],
        ctx=ast.Load())


def _ret_tuple(names):
    return ast.Return(value=ast.Tuple(
        elts=[ast.Name(id=n, ctx=ast.Load()) for n in names],
        ctx=ast.Load()))


def _bind_target(names):
    # always a tuple target — the branch/body fns return tuples even for
    # one name, so `(y,) = call` unpacks consistently
    return ast.Tuple(elts=[ast.Name(id=n, ctx=ast.Store())
                           for n in names], ctx=ast.Store())


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self.counter = 0

    def _fresh(self, hint):
        self.counter += 1
        return f"__d2s_{hint}_{self.counter}"

    def visit_If(self, node):
        self.generic_visit(node)
        if _has_escape(node.body) or _has_escape(node.orelse):
            return node
        body_names = [n for n in _assigned(node.body)
                      if not n.startswith("__d2s")]
        orelse_names = [n for n in _assigned(node.orelse)
                        if not n.startswith("__d2s")]
        names = sorted(set(body_names) | set(orelse_names))
        both = [i for i, n in enumerate(names)
                if n in body_names and n in orelse_names]

        true_name = self._fresh("true")
        false_name = self._fresh("false")
        body = list(node.body) + ([_ret_tuple(names)] if names
                                  else [ast.Return(value=ast.Constant(
                                      value=None))])
        orelse = (list(node.orelse) or [ast.Pass()]) + \
            ([_ret_tuple(names)] if names
             else [ast.Return(value=ast.Constant(value=None))])
        true_def = ast.FunctionDef(name=true_name, args=_args(names),
                                   body=body, decorator_list=[])
        false_def = ast.FunctionDef(name=false_name, args=_args(names),
                                    body=orelse, decorator_list=[])
        call = ast.Call(
            func=ast.Name(id="__d2s_convert_ifelse", ctx=ast.Load()),
            args=[node.test,
                  ast.Name(id=true_name, ctx=ast.Load()),
                  ast.Name(id=false_name, ctx=ast.Load()),
                  ast.Call(func=ast.Name(id="frozenset", ctx=ast.Load()),
                           args=[ast.Tuple(
                               elts=[ast.Constant(value=i) for i in both],
                               ctx=ast.Load())], keywords=[]),
                  _seed_tuple(names)],
            keywords=[])
        stmt = (ast.Assign(targets=[_bind_target(names)], value=call)
                if names else ast.Expr(value=call))
        return [true_def, false_def, stmt]

    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse or _has_escape(node.body):
            return node
        if any(isinstance(n, ast.NamedExpr) for n in ast.walk(node.test)):
            return node  # walrus binds inside the nested test fn
        names = sorted(n for n in set(_assigned(node.body))
                       if not n.startswith("__d2s"))
        if not names:
            return node

        test_name = self._fresh("test")
        body_name = self._fresh("body")
        test_def = ast.FunctionDef(
            name=test_name, args=_args(names),
            body=[ast.Return(value=node.test)], decorator_list=[])
        body_def = ast.FunctionDef(
            name=body_name, args=_args(names),
            body=list(node.body) + [_ret_tuple(names)], decorator_list=[])
        call = ast.Call(
            func=ast.Name(id="__d2s_convert_while", ctx=ast.Load()),
            args=[ast.Name(id=test_name, ctx=ast.Load()),
                  ast.Name(id=body_name, ctx=ast.Load()),
                  ast.Tuple(elts=[ast.Constant(value=n) for n in names],
                            ctx=ast.Load()),
                  _seed_tuple(names)],
            keywords=[])
        assign = ast.Assign(targets=[_bind_target(names)], value=call)
        return [test_def, body_def, assign]


def ast_transform(fn):
    """Control-flow-converted clone of ``fn``, or None when conversion
    isn't possible (no source, closures, nothing to convert, exec
    failure).  Identical behavior for python-bool conditions."""
    if getattr(fn, "__closure__", None):
        return None  # free variables would need cell surgery
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return None
    fdef = tree.body[0]
    if not isinstance(fdef, ast.FunctionDef):
        return None
    fdef.decorator_list = []  # the caller re-wraps

    def _mangled(name):
        return name.startswith("__") and not name.endswith("__")

    for n in ast.walk(fdef):
        # private-name mangling (self.__x -> _Cls__x) happens at class
        # compile time; re-exec at module scope loses it — fall back
        if isinstance(n, ast.Attribute) and _mangled(n.attr):
            return None
        if isinstance(n, ast.Name) and _mangled(n.id):
            return None

    transformer = _ControlFlowTransformer()
    new_tree = transformer.visit(tree)
    if transformer.counter == 0:
        return None
    ast.fix_missing_locations(new_tree)

    try:
        code = compile(new_tree, filename=f"<dy2static {fn.__name__}>",
                       mode="exec")
    except (SyntaxError, ValueError):
        return None
    # exec against the LIVE module globals so helpers defined after
    # decoration (or monkeypatched later) resolve exactly as they would
    # in the original function; only prefixed helper names are injected
    glb = fn.__globals__
    glb["__d2s_convert_ifelse"] = convert_ifelse
    glb["__d2s_convert_while"] = convert_while
    glb["__d2s_get"] = _frame_get
    loc = {}
    try:
        exec(code, glb, loc)
    except Exception:
        return None
    converted = loc.get(fdef.name) or glb.get(fdef.name)
    if converted is None:
        return None
    converted.__defaults__ = fn.__defaults__
    if fn.__kwdefaults__:
        converted.__kwdefaults__ = dict(fn.__kwdefaults__)
    return functools.wraps(fn)(converted)
