"""DataLoader (reference python/paddle/io/reader.py:218).

Single-process iterator with numpy collation; batches become device Tensors
lazily (jax moves data async on first use).  ``num_workers`` is accepted for
parity; a thread-pool prefetcher covers the common TPU-VM case where host
CPUs outrun one chip's consumption.
"""

import queue
import threading

import numpy as np

from ..core.tensor import Tensor
from .dataset import IterableDataset
from .sampler import BatchSampler


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, Tensor):
        return Tensor(np.stack([np.asarray(s._data) for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, np.integer)):
        return Tensor(np.asarray(batch, dtype=np.int64))
    if isinstance(sample, (float, np.floating)):
        return Tensor(np.asarray(batch, dtype=np.float32))
    if isinstance(sample, (list, tuple)):
        transposed = zip(*batch)
        return type(sample)(default_collate_fn(list(s)) for s in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    raise TypeError(f"cannot collate type {type(sample)}")


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset=dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def _iter_batches(self):
        if self._iterable_mode:
            batch = []
            for sample in self.dataset:
                batch.append(sample)
                if len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
        else:
            for indices in self.batch_sampler:
                batch = [self.dataset[i] for i in indices]
                yield self.collate_fn(batch)

    def __iter__(self):
        if self.num_workers and self.num_workers > 0:
            return _PrefetchIterator(self._iter_batches(),
                                     self.prefetch_factor * max(self.num_workers, 1))
        return self._iter_batches()


class _PrefetchIterator:
    _SENTINEL = object()

    def __init__(self, source, depth):
        self._queue = queue.Queue(maxsize=depth)
        self._err = None

        def worker():
            try:
                for item in source:
                    self._queue.put(item)
            except BaseException as e:  # propagate to consumer
                self._err = e
            finally:
                self._queue.put(self._SENTINEL)

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    def __iter__(self):
        return self

    def __next__(self):
        item = self._queue.get()
        if item is self._SENTINEL:
            if self._err is not None:
                raise self._err
            raise StopIteration
        return item
