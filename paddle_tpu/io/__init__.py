"""paddle_tpu.io: Dataset / DataLoader (reference python/paddle/io/).

The reference DataLoader (io/reader.py:218) spins multiprocess workers feeding
a blocking queue; on TPU-VM the host CPUs are plentiful and the device is fed
asynchronously by jax dispatch, so the default loader is a fast single-process
iterator with optional prefetch-to-device; multiprocess workers arrive with
the C++ data pipeline (SURVEY §7 step 10).
"""

from .dataset import (  # noqa: F401
    ChainDataset,
    ComposeDataset,
    ConcatDataset,
    Dataset,
    IterableDataset,
    Subset,
    TensorDataset,
    random_split,
)
from .sampler import (  # noqa: F401
    BatchSampler,
    DistributedBatchSampler,
    RandomSampler,
    Sampler,
    SequenceSampler,
    SubsetRandomSampler,
    WeightedRandomSampler,
)
from .device_prefetch import prefetch_to_device  # noqa: F401
from .dataloader import (  # noqa: F401
    DataLoader,
    default_collate_fn,
    get_worker_info,
)
