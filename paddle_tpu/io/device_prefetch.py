"""Device prefetch — overlap host→HBM transfer with device compute.

Reference analog: the buffered/double-buffered readers feeding GPU
streams (use_buffer_reader in io/reader.py + the PS feed threads).  On
TPU the transfer rides a separate DMA engine, so staging the NEXT
batch's device_put while the CURRENT step computes hides the host→HBM
latency entirely for steady-state training.
"""

import collections

import jax

from ..core.tensor import Tensor

__all__ = ["prefetch_to_device"]


def _stage(batch, device):
    """Start async host->device transfers for every array in the batch."""
    import numpy as np

    def put(x):
        if isinstance(x, Tensor):
            return Tensor(jax.device_put(x._data, device),
                          stop_gradient=x.stop_gradient)
        # only array-like leaves transfer; other payloads pass through
        # untouched (a failing device_put on a REAL array must raise, not
        # silently stay host-resident)
        if isinstance(x, (np.ndarray, jax.Array, int, float, complex,
                          np.generic)):
            return jax.device_put(x, device)
        return x

    return jax.tree_util.tree_map(
        put, batch, is_leaf=lambda x: isinstance(x, Tensor))


def prefetch_to_device(loader, size=2, device=None):
    """Wrap any batch iterable so batches arrive already resident in HBM.

    ``size`` batches are kept in flight (2 = classic double buffering).
    device_put is asynchronous: staging returns immediately and the
    transfer overlaps the consumer's device work.

    >>> for x, y in prefetch_to_device(loader, size=2):
    ...     loss = train_step(x, y)   # transfer of the next batch overlaps
    """
    if device is None:
        device = jax.devices()[0]
    queue = collections.deque()
    it = iter(loader)
    try:
        while True:
            while len(queue) < size:
                queue.append(_stage(next(it), device))
            yield queue.popleft()
    except StopIteration:
        while queue:
            yield queue.popleft()
