"""paddle.text parity — viterbi decoding + dataset scaffolds.

Reference: python/paddle/text/viterbi_decode.py (ViterbiDecoder over the
viterbi_decode op) and text/datasets/ (downloadable corpora — gated here,
no egress).
"""

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..nn.layer_base import Layer
from ..ops.registry import register_external

__all__ = ["viterbi_decode", "ViterbiDecoder"]


def _vit_pure(potentials, transitions, lengths, include_bos_eos_tag):
    """potentials [B, T, N], transitions [N, N], lengths [B] int64.

    Returns (scores [B], paths [B, T]) — best-path score and tag indices;
    positions beyond a sequence's length hold zeros (reference semantics:
    outputs are only meaningful up to lengths[b]).
    """
    b, t, n = potentials.shape
    lengths = lengths.astype(jnp.int32)

    if include_bos_eos_tag:
        # reference convention: tag N-2 = BOS, N-1 = EOS
        bos_idx, eos_idx = n - 2, n - 1
        start = potentials[:, 0] + transitions[bos_idx][None, :]
    else:
        start = potentials[:, 0]

    def step(alpha, inp):
        emit, tpos = inp                      # emit [B, N], tpos scalar
        # score[b, i, j] = alpha[b, i] + trans[i, j] + emit[b, j]
        scores = alpha[:, :, None] + transitions[None, :, :] \
            + emit[:, None, :]
        best_prev = jnp.argmax(scores, axis=1)            # [B, N]
        new_alpha = jnp.max(scores, axis=1)               # [B, N]
        # frozen once past the sequence end
        active = (tpos < lengths)[:, None]
        new_alpha = jnp.where(active, new_alpha, alpha)
        return new_alpha, best_prev

    emits = jnp.moveaxis(potentials[:, 1:], 1, 0)          # [T-1, B, N]
    tpos = jnp.arange(1, t)
    alpha, backptrs = jax.lax.scan(step, start, (emits, tpos))
    # backptrs: [T-1, B, N]

    if include_bos_eos_tag:
        alpha = alpha + transitions[:, n - 1][None, :]

    last_tag = jnp.argmax(alpha, axis=-1)                  # [B]
    scores = jnp.max(alpha, axis=-1)

    def back_step(tag, inp):
        bp, tpos = inp                                     # bp [B, N]
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        # only follow pointers inside the sequence
        tag_prev = jnp.where(tpos < lengths, prev, tag)
        return tag_prev, tag

    rev_bp = backptrs[::-1]
    rev_tpos = tpos[::-1]
    first_tag, rev_path = jax.lax.scan(back_step, last_tag,
                                       (rev_bp, rev_tpos))
    path = jnp.concatenate([first_tag[None], rev_path[::-1]], axis=0)
    path = jnp.moveaxis(path, 0, 1)                        # [B, T]
    # zero out positions past each length (reference: unused tail)
    mask = jnp.arange(t)[None, :] < lengths[:, None]
    path = jnp.where(mask, path, 0)
    return scores, path.astype(jnp.int64)


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """Best tag sequence under a linear-chain CRF (reference
    python/paddle/text/viterbi_decode.py:25)."""
    pot = potentials._data if isinstance(potentials, Tensor) \
        else jnp.asarray(potentials)
    trans = transition_params._data \
        if isinstance(transition_params, Tensor) \
        else jnp.asarray(transition_params)
    lens = lengths._data if isinstance(lengths, Tensor) \
        else jnp.asarray(lengths)
    scores, path = _vit_pure(pot, trans, lens, bool(include_bos_eos_tag))
    return Tensor(scores), Tensor(path)


register_external("viterbi_decode", viterbi_decode, jax_fn=_vit_pure,
                  tags=("text",))


class ViterbiDecoder(Layer):
    """Reference python/paddle/text/viterbi_decode.py:93."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


class _GatedDataset:
    """Downloadable corpora are unavailable (no egress): raise w/ guidance."""

    NAME = "dataset"

    def __init__(self, *a, **k):
        raise RuntimeError(
            f"paddle_tpu.text.{self.NAME}: automatic download is "
            "unavailable in this environment; load the corpus from local "
            "files with paddle_tpu.io.Dataset instead.")


class Imdb(_GatedDataset):
    NAME = "Imdb"


class Conll05st(_GatedDataset):
    NAME = "Conll05st"


class Movielens(_GatedDataset):
    NAME = "Movielens"


class UCIHousing(_GatedDataset):
    NAME = "UCIHousing"


class WMT14(_GatedDataset):
    NAME = "WMT14"


class WMT16(_GatedDataset):
    NAME = "WMT16"
