"""StringTensor + string kernels (reference phi StringTensor at
paddle/phi/core/string_tensor.h and the strings kernel family at
paddle/phi/kernels/strings/ — empty/copy/lower/upper over pstring arrays,
the substrate for the faster-tokenizer path).

TPU redesign: strings never reach the chip (XLA has no string type) — the
reference keeps them on host too.  StringTensor wraps a numpy object
array; kernels are vectorized host ops with the same names
(empty/lower/upper) plus the accessors tokenization pipelines need.
UTF-8 handling comes from Python's str (the reference carries its own
unicode tables, paddle/phi/kernels/strings/unicode.cc).
"""

import numpy as np

__all__ = ["StringTensor", "empty", "lower", "upper", "to_string_tensor"]


class StringTensor:
    """Host-resident tensor of variable-length UTF-8 strings."""

    def __init__(self, data, name=None):
        arr = np.asarray(data, dtype=object)
        self._data = arr
        self.name = name or "string_tensor"

    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def size(self):
        return int(self._data.size)

    def numpy(self):
        return self._data

    def tolist(self):
        return self._data.tolist()

    def __getitem__(self, idx):
        out = self._data[idx]
        return out if isinstance(out, str) else StringTensor(out)

    def __len__(self):
        return len(self._data)

    def __eq__(self, other):
        other_arr = other._data if isinstance(other, StringTensor) \
            else np.asarray(other, dtype=object)
        return self._data == other_arr

    def __repr__(self):
        return f"StringTensor(shape={self.shape}, {self._data!r})"

    # ------------------------------------------------- kernel-like methods --
    def lower(self):
        return _map(self, str.lower)

    def upper(self):
        return _map(self, str.upper)

    def str_len(self):
        """Per-element length in unicode code points -> int32 ndarray."""
        return np.vectorize(len, otypes=[np.int32])(self._data)

    def byte_len(self):
        return np.vectorize(lambda s: len(s.encode("utf-8")),
                            otypes=[np.int32])(self._data)


def _map(st, fn):
    return StringTensor(np.vectorize(fn, otypes=[object])(st._data))


def empty(shape, name=None):
    """strings_empty_kernel parity: StringTensor of empty strings."""
    arr = np.full(tuple(shape), "", dtype=object)
    return StringTensor(arr, name=name)


def lower(x, use_utf8_encoding=True, name=None):
    """strings_lower_upper_kernel parity."""
    return _map(x if isinstance(x, StringTensor) else StringTensor(x),
                str.lower)


def upper(x, use_utf8_encoding=True, name=None):
    return _map(x if isinstance(x, StringTensor) else StringTensor(x),
                str.upper)


def to_string_tensor(data, name=None):
    return StringTensor(data, name=name)
