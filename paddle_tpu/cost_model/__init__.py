"""paddle.cost_model parity — measured per-op cost lookup.

Reference: python/paddle/cost_model/cost_model.py (profile a program,
report per-op times; static_op_benchmark.json lookup for the pass/planner
stack).  TPU redesign: costs come from the framework's own profiler host
events (eager) or from timing jitted ops directly; results are cached and
exportable as JSON — the same role the reference's benchmark json plays
for auto-parallel/tuner decisions.

The *static* cost side (no execution at all) lives in
:mod:`paddle_tpu.framework.cost` — jaxpr-walk FLOPs/HBM/collective
estimates, donation-aware peak memory, rooflines, and the serving
executable census (docs/ANALYSIS.md §"Cost model & executable
census").  Its public surface is re-exported here so
``paddle_tpu.cost_model`` is the one import for both the measured and
the predicted view.
"""

import json
import time

import numpy as np

from ..framework.cost import (CostEstimate, derive_max_batch,
                              engine_memory_model, estimate_jaxpr,
                              estimate_jitted, parse_bytes, run_census,
                              xla_cost_analysis)

__all__ = ["CostModel", "CostEstimate", "estimate_jaxpr",
           "estimate_jitted", "xla_cost_analysis", "run_census",
           "engine_memory_model", "derive_max_batch", "parse_bytes"]


class CostModel:
    def __init__(self):
        self._static_table = {}

    # ------------------------------------------------------------ profile --
    def profile_measure(self, fn, *args, fetch_cost_list=("time",),
                        warmup=2, iters=5):
        """Measure per-op host costs of running ``fn(*args)`` eagerly.

        Returns {op_name: {"op_time_ms": total, "calls": n}} from the
        profiler's RecordEvent stream (the reference profiles a Program
        run and aggregates per-op; here ops are eager dispatches).
        """
        from ..profiler import Profiler

        for _ in range(warmup):
            fn(*args)
        prof = Profiler(timer_only=True)
        prof.start()
        for _ in range(iters):
            fn(*args)
        agg_raw = prof.aggregated_events()
        prof.stop()
        return {name: {"op_time_ms": tot * 1e3 / iters, "calls": cnt}
                for name, (tot, cnt, _mx) in agg_raw.items()}

    # ------------------------------------------------------- static table --
    def measure_op(self, name, shapes=((1024, 1024),), dtype="float32",
                   iters=10):
        """Time one registered op on synthetic inputs (jitted, device)."""
        import jax
        import jax.numpy as jnp

        from ..ops.registry import OPS

        if name not in OPS or OPS[name].jax_fn is None:
            raise KeyError(f"op {name!r} has no pure-jax impl to measure")
        fn = jax.jit(OPS[name].jax_fn)
        rng = np.random.RandomState(0)
        args = [jnp.asarray(rng.rand(*s).astype(dtype)) for s in shapes]
        out = fn(*args)
        jax.block_until_ready(out)
        t = float("inf")
        for _ in range(iters):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*args))
            t = min(t, time.perf_counter() - t0)
        key = f"{name}|{shapes}|{dtype}"
        self._static_table[key] = t * 1e3
        return t * 1e3

    def get_static_op_time(self, op_name, forward=True, dtype="float32",
                           shapes=((1024, 1024),)):
        """Cost (ms) for an op, measuring on first request (the reference
        reads static_op_benchmark.json; ours measures on the live chip)."""
        key = f"{op_name}|{shapes}|{dtype}"
        if key not in self._static_table:
            self.measure_op(op_name, shapes=shapes, dtype=dtype)
        return {"op_time": self._static_table[key]}

    def save(self, path):
        with open(path, "w") as f:
            json.dump(self._static_table, f, indent=1)

    def load(self, path):
        with open(path) as f:
            self._static_table.update(json.load(f))
