"""paddle.audio.functional parity — mel/window/dct math as pure jax.

Reference: python/paddle/audio/functional/{functional,window}.py (hz↔mel,
fbank matrices, dct basis, windows, power_to_db).  Implementations are
standard DSP formulas over jnp; everything jits and differentiates.
"""

import math

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..ops.registry import register_external

__all__ = ["hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
           "compute_fbank_matrix", "power_to_db", "create_dct",
           "get_window"]


def _data(x):
    return x._data if isinstance(x, Tensor) else x


def hz_to_mel(freq, htk=False):
    """Hertz → mel.  Slaney (default) or HTK scale (reference parity)."""
    f = _data(freq)
    scalar = np.isscalar(freq)
    f = jnp.asarray(f, jnp.float32)
    if htk:
        mel = 2595.0 * jnp.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        mel = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        mel = jnp.where(f >= min_log_hz,
                        min_log_mel + jnp.log(
                            jnp.maximum(f, 1e-10) / min_log_hz) / logstep,
                        mel)
    return float(mel) if scalar else Tensor(mel) \
        if isinstance(freq, Tensor) else mel


def mel_to_hz(mel, htk=False):
    m = _data(mel)
    scalar = np.isscalar(mel)
    m = jnp.asarray(m, jnp.float32)
    if htk:
        hz = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        hz = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        hz = jnp.where(m >= min_log_mel,
                       min_log_hz * jnp.exp(logstep * (m - min_log_mel)),
                       hz)
    return float(hz) if scalar else Tensor(hz) \
        if isinstance(mel, Tensor) else hz


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False):
    low = hz_to_mel(float(f_min), htk)
    high = hz_to_mel(float(f_max), htk)
    mels = jnp.linspace(low, high, n_mels)
    return mel_to_hz(mels, htk)


def fft_frequencies(sr, n_fft):
    return jnp.linspace(0.0, float(sr) / 2, n_fft // 2 + 1)


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney"):
    """[n_mels, n_fft//2 + 1] triangular mel filterbank."""
    if f_max is None:
        f_max = float(sr) / 2
    fftfreqs = fft_frequencies(sr, n_fft)                  # [F]
    melfreqs = mel_frequencies(n_mels + 2, f_min, f_max, htk)  # [M+2]
    fdiff = jnp.diff(melfreqs)
    ramps = melfreqs[:, None] - fftfreqs[None, :]          # [M+2, F]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = jnp.maximum(0.0, jnp.minimum(lower, upper))  # [M, F]
    if norm == "slaney":
        enorm = 2.0 / (melfreqs[2:n_mels + 2] - melfreqs[:n_mels])
        weights = weights * enorm[:, None]
    return weights


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
    x = jnp.asarray(_data(spect))
    log_spec = 10.0 * jnp.log10(jnp.maximum(amin, x))
    log_spec = log_spec - 10.0 * math.log10(max(amin, ref_value))
    if top_db is not None:
        log_spec = jnp.maximum(log_spec, log_spec.max() - top_db)
    return Tensor(log_spec) if isinstance(spect, Tensor) else log_spec


def create_dct(n_mfcc, n_mels, norm="ortho"):
    """[n_mels, n_mfcc] DCT-II basis (reference create_dct)."""
    n = jnp.arange(n_mels, dtype=jnp.float32)
    k = jnp.arange(n_mfcc, dtype=jnp.float32)
    dct = jnp.cos(math.pi / n_mels * (n[:, None] + 0.5) * k[None, :])
    if norm == "ortho":
        dct = dct * math.sqrt(2.0 / n_mels)
        dct = dct.at[:, 0].set(dct[:, 0] * (1.0 / math.sqrt(2)))
    else:
        dct = dct * 2.0
    return dct


_WINDOWS = {}


def _win_hann(n, periodic):
    m = n if periodic else n - 1
    return 0.5 - 0.5 * jnp.cos(2 * math.pi * jnp.arange(n) / max(m, 1))


def _win_hamming(n, periodic):
    m = n if periodic else n - 1
    return 0.54 - 0.46 * jnp.cos(2 * math.pi * jnp.arange(n) / max(m, 1))


def _win_blackman(n, periodic):
    m = n if periodic else n - 1
    t = 2 * math.pi * jnp.arange(n) / max(m, 1)
    return 0.42 - 0.5 * jnp.cos(t) + 0.08 * jnp.cos(2 * t)


_WINDOWS.update(hann=_win_hann, hamming=_win_hamming,
                blackman=_win_blackman,
                rect=lambda n, periodic: jnp.ones(n))
_WINDOWS["boxcar"] = _WINDOWS["rect"]


def get_window(window, win_length, fftbins=True):
    if isinstance(window, tuple):  # ("gaussian", std) style: unsupported tail
        window = window[0]
    if window not in _WINDOWS:
        raise ValueError(f"unsupported window {window!r}; "
                         f"have {sorted(_WINDOWS)}")
    return _WINDOWS[window](int(win_length), bool(fftbins)) \
        .astype(jnp.float32)


for _name in ("hz_to_mel", "mel_to_hz", "compute_fbank_matrix",
              "power_to_db"):
    register_external(f"audio.{_name}", globals()[_name], tags=("audio",))
