"""paddle.audio.features parity — Spectrogram / MelSpectrogram /
LogMelSpectrogram / MFCC as nn.Layers.

Reference: python/paddle/audio/features/layers.py.  Built on the
framework's own stft (paddle_tpu/signal.py) and the mel/dct math in
audio.functional; the whole pipeline is jax — it jits, differentiates,
and runs on device.
"""

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.layer_base import Layer
from .. import signal as _signal
from . import functional as F

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


class Spectrogram(Layer):
    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.window = F.get_window(window, self.win_length)

    def forward(self, x):
        spec = _signal.stft(x, self.n_fft, hop_length=self.hop_length,
                            win_length=self.win_length,
                            window=Tensor(self.window), center=self.center,
                            pad_mode=self.pad_mode)
        data = spec._data if isinstance(spec, Tensor) else spec
        mag = jnp.abs(data)
        if self.power != 1.0:
            mag = mag ** self.power
        return Tensor(mag)


class MelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False,
                 norm="slaney", dtype="float32"):
        super().__init__()
        self._spectrogram = Spectrogram(
            n_fft=n_fft, hop_length=hop_length, win_length=win_length,
            window=window, power=power, center=center, pad_mode=pad_mode)
        self.fbank = F.compute_fbank_matrix(
            sr=sr, n_fft=n_fft, n_mels=n_mels, f_min=f_min, f_max=f_max,
            htk=htk, norm=norm)

    def forward(self, x):
        spec = self._spectrogram(x)._data     # [..., freq, time]
        mel = jnp.einsum("mf,...ft->...mt", self.fbank, spec)
        return Tensor(mel)


class LogMelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False,
                 norm="slaney", ref_value=1.0, amin=1e-10, top_db=None,
                 dtype="float32"):
        super().__init__()
        self._mel = MelSpectrogram(
            sr=sr, n_fft=n_fft, hop_length=hop_length, win_length=win_length,
            window=window, power=power, center=center, pad_mode=pad_mode,
            n_mels=n_mels, f_min=f_min, f_max=f_max, htk=htk, norm=norm)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        mel = self._mel(x)
        return Tensor(F.power_to_db(mel._data, ref_value=self.ref_value,
                                    amin=self.amin, top_db=self.top_db))


class MFCC(Layer):
    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self._log_mel = LogMelSpectrogram(
            sr=sr, n_fft=n_fft, hop_length=hop_length, win_length=win_length,
            window=window, power=power, center=center, pad_mode=pad_mode,
            n_mels=n_mels, f_min=f_min, f_max=f_max, htk=htk, norm=norm,
            ref_value=ref_value, amin=amin, top_db=top_db)
        self.dct = F.create_dct(n_mfcc, n_mels)

    def forward(self, x):
        logmel = self._log_mel(x)._data       # [..., n_mels, time]
        mfcc = jnp.einsum("mk,...mt->...kt", self.dct, logmel)
        return Tensor(mfcc)
