"""paddle.audio parity — feature layers + DSP functional.

Reference: python/paddle/audio/ (features, functional, backends,
datasets).  Backends (soundfile IO) and downloadable datasets are gated:
this environment has no egress and no soundfile; ``load``/``save`` raise
with guidance, while the compute path (spectrogram/mel/mfcc) is fully
native jax (see features.py / functional.py).
"""

from . import features, functional  # noqa: F401


def load(*args, **kwargs):
    raise NotImplementedError(
        "paddle_tpu.audio.load requires an audio IO backend (soundfile); "
        "decode to numpy yourself and feed the array to audio.features.")


def save(*args, **kwargs):
    raise NotImplementedError(
        "paddle_tpu.audio.save requires an audio IO backend (soundfile).")


class datasets:
    """Downloadable audio corpora (reference audio/datasets/: TESS, ESC50)
    are unavailable without egress; the classes raise with guidance."""

    class _Gated:
        def __init__(self, *a, **k):
            raise RuntimeError(
                f"paddle_tpu.audio.datasets.{type(self).__name__}: "
                "automatic download is unavailable (no egress); decode "
                "local files and feed arrays through audio.features.")

    class TESS(_Gated):
        pass

    class ESC50(_Gated):
        pass
