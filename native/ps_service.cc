// Parameter-server RPC service — multi-host sparse tables over DCN.
//
// Role parity with the reference's brpc PS data plane
// (paddle/fluid/distributed/ps/service/brpc_ps_client.cc /
// brpc_ps_server.cc): trainers pull/push embedding rows from table shards
// hosted on remote processes.  Design here is new and much smaller: a
// blocking thread-per-connection TCP server speaking length-prefixed
// binary frames directly over the pd_table_* C ABI (sparse_table.cc), with
// key->server sharding done by the client layer (key % num_servers).
//
// FINAL DECISION (round 5, closes the carried epoll question): the IO
// model IS thread-per-connection; the epoll/worker-pool rewrite is
// REJECTED, not deferred.  Rationale: (1) the measured plateau below is
// table-mutex/memcpy-bound, so a reactor would not raise aggregate
// throughput; (2) each trainer holds exactly one connection per server,
// bounding threads at trainer_count — three orders of magnitude under
// where reactors pay off; (3) horizontal scaling is already built in
// (key % num_servers sharding -> more server processes).  Revisit ONLY
// if a deployment needs >10k concurrent connections per process, which
// contradicts the one-connection-per-trainer topology.
//
// Scale ceiling (deliberate): one OS thread per trainer connection.
// Linux handles thousands of mostly-idle threads fine, and each trainer
// holds exactly ONE connection per server, so the ceiling is
// ~trainer_count threads per server — comfortable to O(1k) trainers
// (≈8 MB stack-reserve each, demand-paged).  The reference's brpc epoll
// reactor exists to serve tens of thousands of mixed client types; if a
// deployment needs that, put the shards behind more server PROCESSES
// (key-sharding already spreads load) before reaching for epoll here.
//
// MEASURED (benchmarks/bench_ps_service.py, 256-key dim-16 batches,
// loopback, 2026-07 dev VM): 1 client ≈30k RPC/s; 8 clients ≈26k;
// 32 clients ≈21k (≈5.4M rows/s aggregate); 64 clients ≈20k.  The
// ~30% aggregate droop from 1→64 is shard-map mutex + memcpy CPU on
// the single table, NOT thread scheduling — throughput plateaus
// rather than collapsing, so the thread-per-connection ceiling claim
// holds to at least 64 concurrent trainers per shard.  Correctness
// under 32-way mixed pull/push contention is pinned by
// tests/test_ps_service.py::test_32_concurrent_clients_mixed_pull_push.
//
// Wire format (little-endian):
//   request : u8 opcode | u64 payload_len | payload
//     PULL payload: i64 n | i64 keys[n]
//     PUSH payload: u8 opt(0 sgd,1 adagrad) | f32 lr | f32 eps
//                   | i64 n | i64 keys[n] | f32 grads[n*dim]
//     SAVE/LOAD payload: path bytes
//     SIZE/DIM payload: none
//   response: i32 rc(0 ok) | u64 data_len | data
#include "paddle_native.h"

#include <arpa/inet.h>
#include <errno.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

enum PsOp : uint8_t {
  kPull = 1,
  kPush = 2,
  kSave = 3,
  kLoad = 4,
  kSize = 5,
  kDim = 6,
  kPushDelta = 7,   // GeoSGD: w += delta
  kShowClick = 8,   // CTR accessor stats
  kShrink = 9,      // decay + evict cycle; replies evicted count
  kStats = 10,      // (mem_rows, disk_rows)
  kGeoInit = 11,    // i32 trainer_num — enable per-trainer delta queues
  kGeoPush = 12,    // i32 trainer_id | i64 n | keys[n] | deltas[n*dim]
  kGeoPull = 13,    // i32 trainer_id | i64 max_n -> i64 n|keys|rows
  kGeoPullCount = 14,  // i32 trainer_id -> i64 queued (client buffer
                       // sizing: 12 bytes in must not buy GiB allocs)
  // graph table verbs (GraphPS role; server started with a graph handle)
  kGraphAddEdges = 20,  // i64 n | u8 weighted | src[n] | dst[n] | [w[n]]
  kGraphSample = 21,    // i64 n | i32 k | nodes[n] -> nbrs[n*k]|counts[n]
  kGraphDegrees = 22,   // i64 n | nodes[n] -> degrees[n]
  kGraphSize = 23,      // -> (num_nodes, num_edges)
  kGraphSave = 24,
  kGraphLoad = 25,
};

constexpr uint64_t kMaxPayload = 1ull << 32;  // 4 GiB per request

thread_local std::string g_ps_error;
void ps_error(const std::string& m) { g_ps_error = m; }

bool io_send_all(int fd, const void* data, size_t len) {
  const char* p = static_cast<const char*>(data);
  while (len) {
    ssize_t n = send(fd, p, len, MSG_NOSIGNAL);
    if (n < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += n;
    len -= n;
  }
  return true;
}

bool io_recv_all(int fd, void* data, size_t len, int timeout_ms) {
  char* p = static_cast<char*>(data);
  while (len) {
    if (timeout_ms > 0) {
      pollfd pfd{fd, POLLIN, 0};
      int r = poll(&pfd, 1, timeout_ms);
      if (r == 0) { ps_error("ps recv timeout"); return false; }
      if (r < 0) {
        if (errno == EINTR) continue;
        return false;
      }
    }
    ssize_t n = recv(fd, p, len, 0);
    if (n <= 0) {
      if (n < 0 && errno == EINTR) continue;
      return false;
    }
    p += n;
    len -= n;
  }
  return true;
}

// One record per connection.  The handler thread never closes its fd: it
// marks `done` and the pruner (accept loop, or stop()) closes the fd after
// joining the thread — so a stale fd number can never be shutdown() after
// the kernel recycled it for an unrelated descriptor.
struct ConnRec {
  int fd = -1;
  std::atomic<bool> done{false};
  std::thread th;
};

struct PsServer {
  void* table = nullptr;  // borrowed pd_table handle (not owned)
  void* graph = nullptr;  // borrowed pd_graph handle (graph servers)
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> stopping{false};
  std::thread accept_thread;
  std::mutex conn_mu;
  std::vector<ConnRec*> conns;
};

void reply(int fd, int32_t rc, const void* data, uint64_t len) {
  std::string hdr;
  hdr.append(reinterpret_cast<char*>(&rc), 4);
  hdr.append(reinterpret_cast<char*>(&len), 8);
  if (!io_send_all(fd, hdr.data(), hdr.size())) return;
  if (len) io_send_all(fd, data, len);
}

void handle_conn(PsServer* s, ConnRec* rec) try {
  int fd = rec->fd;
  int dim = s->table ? pd_table_dim(s->table) : 0;
  // per-request row cap: keys fit the payload (plen/8) AND the pull reply
  // buffer stays under ~2 GiB of floats
  const uint64_t kMaxRowFloats = 1ull << 29;
  std::vector<char> payload;
  while (!s->stopping.load()) {
    uint8_t op;
    uint64_t plen;
    if (!io_recv_all(fd, &op, 1, 0)) break;
    if (!io_recv_all(fd, &plen, 8, 0)) break;
    if (plen > kMaxPayload) break;  // corrupt stream
    payload.resize(plen);
    if (plen && !io_recv_all(fd, payload.data(), plen, 0)) break;

    if ((op >= kGraphAddEdges) ? (s->graph == nullptr)
                               : (s->table == nullptr && op != kDim)) {
      reply(fd, -6, nullptr, 0);  // verb not served by this endpoint
      continue;
    }
    switch (op) {
      case kPull: {
        if (plen < 8) { reply(fd, -3, nullptr, 0); break; }
        int64_t n;
        memcpy(&n, payload.data(), 8);
        if (n < 0 || static_cast<uint64_t>(n) > plen / 8 ||
            plen != 8 + static_cast<uint64_t>(n) * 8 ||
            static_cast<uint64_t>(n) * dim > kMaxRowFloats) {
          reply(fd, -3, nullptr, 0);
          break;
        }
        const int64_t* keys =
            reinterpret_cast<const int64_t*>(payload.data() + 8);
        std::vector<float> rows(static_cast<size_t>(n) * dim);
        pd_table_pull(s->table, keys, n, rows.data());
        reply(fd, 0, rows.data(), rows.size() * sizeof(float));
        break;
      }
      case kPush: {
        if (plen < 1 + 4 + 4 + 8) { reply(fd, -3, nullptr, 0); break; }
        uint8_t opt = static_cast<uint8_t>(payload[0]);
        float lr, eps;
        int64_t n;
        memcpy(&lr, payload.data() + 1, 4);
        memcpy(&eps, payload.data() + 5, 4);
        memcpy(&n, payload.data() + 9, 8);
        // bound n by the payload BEFORE computing sizes so the uint64
        // arithmetic below cannot wrap on a crafted frame
        if (n < 0 || static_cast<uint64_t>(n) > plen / 8 ||
            static_cast<uint64_t>(n) * dim > kMaxRowFloats) {
          reply(fd, -3, nullptr, 0);
          break;
        }
        uint64_t want = 17 + static_cast<uint64_t>(n) * 8 +
                        static_cast<uint64_t>(n) * dim * 4;
        if (plen != want) { reply(fd, -3, nullptr, 0); break; }
        const int64_t* keys =
            reinterpret_cast<const int64_t*>(payload.data() + 17);
        const float* grads =
            reinterpret_cast<const float*>(payload.data() + 17 + n * 8);
        if (opt == 0)
          pd_table_push_sgd(s->table, keys, grads, n, lr);
        else
          pd_table_push_adagrad(s->table, keys, grads, n, lr, eps);
        reply(fd, 0, nullptr, 0);
        break;
      }
      case kPushDelta: {
        if (plen < 8) { reply(fd, -3, nullptr, 0); break; }
        int64_t n;
        memcpy(&n, payload.data(), 8);
        if (n < 0 || static_cast<uint64_t>(n) > plen / 8 ||
            static_cast<uint64_t>(n) * dim > kMaxRowFloats ||
            plen != 8 + static_cast<uint64_t>(n) * 8 +
                        static_cast<uint64_t>(n) * dim * 4) {
          reply(fd, -3, nullptr, 0);
          break;
        }
        const int64_t* keys =
            reinterpret_cast<const int64_t*>(payload.data() + 8);
        const float* deltas =
            reinterpret_cast<const float*>(payload.data() + 8 + n * 8);
        pd_table_push_delta(s->table, keys, deltas, n);
        reply(fd, 0, nullptr, 0);
        break;
      }
      case kGeoInit: {
        if (plen != 4) { reply(fd, -3, nullptr, 0); break; }
        int32_t tn;
        memcpy(&tn, payload.data(), 4);
        int rc = pd_table_geo_init(s->table, tn);
        reply(fd, rc, nullptr, 0);
        break;
      }
      case kGeoPush: {
        if (plen < 12) { reply(fd, -3, nullptr, 0); break; }
        int32_t tid;
        int64_t n;
        memcpy(&tid, payload.data(), 4);
        memcpy(&n, payload.data() + 4, 8);
        if (n < 0 || static_cast<uint64_t>(n) > plen / 8 ||
            static_cast<uint64_t>(n) * dim > kMaxRowFloats ||
            plen != 12 + static_cast<uint64_t>(n) * 8 +
                         static_cast<uint64_t>(n) * dim * 4) {
          reply(fd, -3, nullptr, 0);
          break;
        }
        const int64_t* keys =
            reinterpret_cast<const int64_t*>(payload.data() + 12);
        const float* deltas =
            reinterpret_cast<const float*>(payload.data() + 12 + n * 8);
        int rc = pd_table_geo_push(s->table, tid, keys, deltas, n);
        reply(fd, rc == 0 ? 0 : -4, nullptr, 0);
        break;
      }
      case kGeoPull: {
        if (plen != 12) { reply(fd, -3, nullptr, 0); break; }
        int32_t tid;
        int64_t max_n;
        memcpy(&tid, payload.data(), 4);
        memcpy(&max_n, payload.data() + 4, 8);
        if (max_n < 0 || static_cast<uint64_t>(max_n) * dim >
                             kMaxRowFloats) {
          reply(fd, -3, nullptr, 0);
          break;
        }
        // buffers size from the REAL queue, never the client's max_n:
        // a 12-byte frame must not buy multi-GiB allocations
        int64_t queued = pd_table_geo_pull_count(s->table, tid);
        if (queued < 0) { reply(fd, -4, nullptr, 0); break; }
        max_n = std::min(max_n, queued);
        std::vector<int64_t> keys(max_n);
        std::vector<float> vals(static_cast<size_t>(max_n) * dim);
        int64_t got = pd_table_geo_pull(s->table, tid, keys.data(),
                                        vals.data(), max_n);
        if (got < 0) { reply(fd, -4, nullptr, 0); break; }
        std::string out(8 + got * 8 + got * dim * 4, '\0');
        memcpy(&out[0], &got, 8);
        memcpy(&out[8], keys.data(), got * 8);
        memcpy(&out[8 + got * 8], vals.data(), got * dim * 4);
        reply(fd, 0, out.data(), out.size());
        break;
      }
      case kGeoPullCount: {
        if (plen != 4) { reply(fd, -3, nullptr, 0); break; }
        int32_t tid;
        memcpy(&tid, payload.data(), 4);
        int64_t queued = pd_table_geo_pull_count(s->table, tid);
        if (queued < 0) { reply(fd, -4, nullptr, 0); break; }
        reply(fd, 0, &queued, 8);
        break;
      }
      case kShowClick: {
        if (plen < 8) { reply(fd, -3, nullptr, 0); break; }
        int64_t n;
        memcpy(&n, payload.data(), 8);
        if (n < 0 || static_cast<uint64_t>(n) > plen / 8 ||
            plen != 8 + static_cast<uint64_t>(n) * 16) {
          reply(fd, -3, nullptr, 0);
          break;
        }
        const int64_t* keys =
            reinterpret_cast<const int64_t*>(payload.data() + 8);
        const float* shows =
            reinterpret_cast<const float*>(payload.data() + 8 + n * 8);
        const float* clicks = shows + n;
        pd_table_push_show_click(s->table, keys, shows, clicks, n);
        reply(fd, 0, nullptr, 0);
        break;
      }
      case kShrink: {
        int64_t evicted = pd_table_shrink(s->table);
        reply(fd, 0, &evicted, 8);
        break;
      }
      case kStats: {
        int64_t stats[2] = {pd_table_mem_rows(s->table),
                            pd_table_disk_rows(s->table)};
        reply(fd, 0, stats, 16);
        break;
      }
      case kSave: {
        std::string path(payload.data(), plen);
        int rc = pd_table_save(s->table, path.c_str());
        reply(fd, rc, nullptr, 0);
        break;
      }
      case kLoad: {
        std::string path(payload.data(), plen);
        int rc = pd_table_load(s->table, path.c_str());
        reply(fd, rc, nullptr, 0);
        break;
      }
      case kSize: {
        int64_t sz = pd_table_size(s->table);
        reply(fd, 0, &sz, 8);
        break;
      }
      case kDim: {
        int32_t d = dim;
        reply(fd, 0, &d, 4);
        break;
      }
      case kGraphAddEdges: {
        if (plen < 9) { reply(fd, -3, nullptr, 0); break; }
        int64_t n;
        uint8_t weighted;
        memcpy(&n, payload.data(), 8);
        weighted = static_cast<uint8_t>(payload[8]);
        uint64_t want = 9 + static_cast<uint64_t>(n) * 16 +
                        (weighted ? static_cast<uint64_t>(n) * 4 : 0);
        if (n < 0 || static_cast<uint64_t>(n) > plen / 16 ||
            plen != want) {
          reply(fd, -3, nullptr, 0);
          break;
        }
        const int64_t* src =
            reinterpret_cast<const int64_t*>(payload.data() + 9);
        const int64_t* dst = src + n;
        const float* w = weighted
            ? reinterpret_cast<const float*>(payload.data() + 9 + n * 16)
            : nullptr;
        pd_graph_add_edges(s->graph, src, dst, w, n);
        reply(fd, 0, nullptr, 0);
        break;
      }
      case kGraphSample: {
        if (plen < 12) { reply(fd, -3, nullptr, 0); break; }
        int64_t n;
        int32_t kk;
        memcpy(&n, payload.data(), 8);
        memcpy(&kk, payload.data() + 8, 4);
        if (n < 0 || kk <= 0 || kk > 4096 ||
            static_cast<uint64_t>(n) > plen / 8 ||
            plen != 12 + static_cast<uint64_t>(n) * 8 ||
            static_cast<uint64_t>(n) * kk > (1ull << 27)) {
          // reply cap ~1 GiB of i64s — the kPull kMaxRowFloats analog
          reply(fd, -3, nullptr, 0);
          break;
        }
        const int64_t* nodes =
            reinterpret_cast<const int64_t*>(payload.data() + 12);
        std::vector<int64_t> nbrs(static_cast<size_t>(n) * kk);
        std::vector<int64_t> counts(n);
        pd_graph_sample_neighbors(s->graph, nodes, n, kk, nbrs.data(),
                                  counts.data());
        std::string data;
        data.append(reinterpret_cast<char*>(nbrs.data()), nbrs.size() * 8);
        data.append(reinterpret_cast<char*>(counts.data()), n * 8);
        reply(fd, 0, data.data(), data.size());
        break;
      }
      case kGraphDegrees: {
        if (plen < 8) { reply(fd, -3, nullptr, 0); break; }
        int64_t n;
        memcpy(&n, payload.data(), 8);
        if (n < 0 || static_cast<uint64_t>(n) > plen / 8 ||
            plen != 8 + static_cast<uint64_t>(n) * 8) {
          reply(fd, -3, nullptr, 0);
          break;
        }
        const int64_t* nodes =
            reinterpret_cast<const int64_t*>(payload.data() + 8);
        std::vector<int64_t> degs(n);
        pd_graph_degrees(s->graph, nodes, n, degs.data());
        reply(fd, 0, degs.data(), static_cast<uint64_t>(n) * 8);
        break;
      }
      case kGraphSize: {
        int64_t sz[2] = {pd_graph_num_nodes(s->graph),
                         pd_graph_num_edges(s->graph)};
        reply(fd, 0, sz, 16);
        break;
      }
      case kGraphSave: {
        std::string path(payload.data(), plen);
        reply(fd, pd_graph_save(s->graph, path.c_str()), nullptr, 0);
        break;
      }
      case kGraphLoad: {
        std::string path(payload.data(), plen);
        reply(fd, pd_graph_load(s->graph, path.c_str()), nullptr, 0);
        break;
      }
      default:
        reply(fd, -2, nullptr, 0);
    }
  }
  rec->done.store(true);  // fd closed by the pruner after join
} catch (...) {
  // never let bad_alloc (oversized frame) escape the thread and terminate
  // the PS host; drop this connection only
  rec->done.store(true);
}

// join + close + erase finished connections (caller holds conn_mu)
void prune_conns(PsServer* s) {
  for (size_t i = 0; i < s->conns.size();) {
    ConnRec* rec = s->conns[i];
    if (rec->done.load()) {
      if (rec->th.joinable()) rec->th.join();
      if (rec->fd >= 0) close(rec->fd);
      delete rec;
      s->conns.erase(s->conns.begin() + i);
    } else {
      ++i;
    }
  }
}

void accept_loop(PsServer* s) {
  while (!s->stopping.load()) {
    pollfd pfd{s->listen_fd, POLLIN, 0};
    int r = poll(&pfd, 1, 500);
    {
      std::lock_guard<std::mutex> lk(s->conn_mu);
      prune_conns(s);
    }
    if (r <= 0) continue;
    int fd = accept(s->listen_fd, nullptr, nullptr);
    if (fd < 0) continue;
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    auto* rec = new ConnRec;
    rec->fd = fd;
    std::lock_guard<std::mutex> lk(s->conn_mu);
    s->conns.push_back(rec);
    rec->th = std::thread(handle_conn, s, rec);
  }
}

struct PsClient {
  int fd = -1;
  int timeout_ms = 30000;
  int dim = 0;
};

// one request/response; any failure poisons the connection (stream desync)
bool ps_request(PsClient* c, uint8_t op, const std::string& payload,
                int32_t* rc, std::string* data) {
  if (c->fd < 0) {
    ps_error("ps connection previously failed");
    return false;
  }
  std::string req;
  req.push_back(static_cast<char>(op));
  uint64_t plen = payload.size();
  req.append(reinterpret_cast<char*>(&plen), 8);
  req.append(payload);
  if (!io_send_all(c->fd, req.data(), req.size())) {
    close(c->fd);
    c->fd = -1;
    return false;
  }
  int32_t code;
  uint64_t dlen;
  if (!io_recv_all(c->fd, &code, 4, c->timeout_ms) ||
      !io_recv_all(c->fd, &dlen, 8, c->timeout_ms) || dlen > kMaxPayload) {
    close(c->fd);
    c->fd = -1;
    return false;
  }
  data->resize(dlen);
  if (dlen && !io_recv_all(c->fd, &data->front(), dlen, c->timeout_ms)) {
    close(c->fd);
    c->fd = -1;
    return false;
  }
  *rc = code;
  return true;
}

}  // namespace

extern "C" {

static void* ps_server_start_impl(void* table, void* graph, int port);

void* pd_ps_server_start(void* table, int port) {
  return ps_server_start_impl(table, nullptr, port);
}

void* pd_ps_graph_server_start(void* graph, int port) {
  return ps_server_start_impl(nullptr, graph, port);
}

static void* ps_server_start_impl(void* table, void* graph, int port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) { ps_error("socket failed"); return nullptr; }
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof addr) < 0 ||
      listen(fd, 64) < 0) {
    ps_error(std::string("bind/listen: ") + strerror(errno));
    close(fd);
    return nullptr;
  }
  socklen_t alen = sizeof addr;
  getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  auto* s = new PsServer;
  s->table = table;
  s->graph = graph;
  s->listen_fd = fd;
  s->port = ntohs(addr.sin_port);
  s->accept_thread = std::thread(accept_loop, s);
  return s;
}

int pd_ps_server_port(void* server) {
  return server ? static_cast<PsServer*>(server)->port : -1;
}

void pd_ps_server_stop(void* server) {
  if (!server) return;
  auto* s = static_cast<PsServer*>(server);
  s->stopping.store(true);
  // join the accept thread FIRST so no new connection can slip in after we
  // shut the existing ones down (the late-accept handler would otherwise
  // block forever in recv and hang the join below)
  if (s->accept_thread.joinable()) s->accept_thread.join();
  {
    std::lock_guard<std::mutex> lk(s->conn_mu);
    // unblock live handlers stuck in recv; their fds are still owned by
    // the ConnRec (handlers never close fds), so no recycled-fd hazard
    for (ConnRec* rec : s->conns)
      if (!rec->done.load() && rec->fd >= 0) shutdown(rec->fd, SHUT_RDWR);
    for (ConnRec* rec : s->conns)
      if (rec->th.joinable()) rec->th.join();
    for (ConnRec* rec : s->conns) {
      if (rec->fd >= 0) close(rec->fd);
      delete rec;
    }
    s->conns.clear();
  }
  close(s->listen_fd);
  delete s;  // table is borrowed; caller destroys it
}

void* pd_ps_client_connect(const char* host, int port, int timeout_ms) {
  // reuse the store client's retrying connector semantics via a plain
  // blocking connect loop (servers may come up after trainers)
  addrinfo hints{}, *res = nullptr;
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  char portstr[16];
  snprintf(portstr, sizeof portstr, "%d", port);
  if (getaddrinfo(host, portstr, &hints, &res) != 0 || !res) {
    ps_error(std::string("getaddrinfo failed for ") + host);
    return nullptr;
  }
  int fd = -1;
  int waited = 0;
  while (true) {
    fd = socket(res->ai_family, res->ai_socktype, res->ai_protocol);
    if (fd < 0) { freeaddrinfo(res); return nullptr; }
    if (connect(fd, res->ai_addr, res->ai_addrlen) == 0) break;
    close(fd);
    fd = -1;
    if (waited >= timeout_ms) {
      ps_error(std::string("ps connect timeout to ") + host + ":" + portstr);
      freeaddrinfo(res);
      return nullptr;
    }
    usleep(200 * 1000);
    waited += 200;
  }
  freeaddrinfo(res);
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
  auto* c = new PsClient;
  c->fd = fd;
  c->timeout_ms = timeout_ms;
  // cache table dim
  int32_t rc;
  std::string data;
  if (!ps_request(c, kDim, "", &rc, &data) || rc != 0 || data.size() != 4) {
    close(c->fd);
    delete c;
    ps_error("ps dim handshake failed");
    return nullptr;
  }
  memcpy(&c->dim, data.data(), 4);
  return c;
}

void pd_ps_client_close(void* client) {
  if (!client) return;
  auto* c = static_cast<PsClient*>(client);
  if (c->fd >= 0) close(c->fd);
  delete c;
}

int pd_ps_client_dim(void* client) {
  return client ? static_cast<PsClient*>(client)->dim : -1;
}

int64_t pd_ps_client_size(void* client) {
  auto* c = static_cast<PsClient*>(client);
  int32_t rc;
  std::string data;
  if (!ps_request(c, kSize, "", &rc, &data) || rc != 0 || data.size() != 8)
    return -1;
  int64_t sz;
  memcpy(&sz, data.data(), 8);
  return sz;
}

int pd_ps_client_pull(void* client, const int64_t* keys, int64_t n,
                      float* out) {
  auto* c = static_cast<PsClient*>(client);
  std::string payload;
  payload.append(reinterpret_cast<const char*>(&n), 8);
  payload.append(reinterpret_cast<const char*>(keys), n * 8);
  int32_t rc;
  std::string data;
  if (!ps_request(c, kPull, payload, &rc, &data)) return -1;
  if (rc != 0) return rc;
  if (data.size() != static_cast<size_t>(n) * c->dim * 4) return -4;
  memcpy(out, data.data(), data.size());
  return 0;
}

int pd_ps_client_push(void* client, int opt, const int64_t* keys,
                      const float* grads, int64_t n, float lr, float eps) {
  auto* c = static_cast<PsClient*>(client);
  std::string payload;
  payload.push_back(static_cast<char>(opt));
  payload.append(reinterpret_cast<const char*>(&lr), 4);
  payload.append(reinterpret_cast<const char*>(&eps), 4);
  payload.append(reinterpret_cast<const char*>(&n), 8);
  payload.append(reinterpret_cast<const char*>(keys), n * 8);
  payload.append(reinterpret_cast<const char*>(grads),
                 static_cast<size_t>(n) * c->dim * 4);
  int32_t rc;
  std::string data;
  if (!ps_request(c, kPush, payload, &rc, &data)) return -1;
  return rc;
}

int pd_ps_client_push_delta(void* client, const int64_t* keys,
                            const float* deltas, int64_t n) {
  auto* c = static_cast<PsClient*>(client);
  std::string payload;
  payload.append(reinterpret_cast<const char*>(&n), 8);
  payload.append(reinterpret_cast<const char*>(keys), n * 8);
  payload.append(reinterpret_cast<const char*>(deltas),
                 static_cast<size_t>(n) * c->dim * 4);
  int32_t rc;
  std::string data;
  if (!ps_request(c, kPushDelta, payload, &rc, &data)) return -1;
  return rc;
}

int pd_ps_client_geo_init(void* client, int32_t trainer_num) {
  auto* c = static_cast<PsClient*>(client);
  std::string payload(reinterpret_cast<const char*>(&trainer_num), 4);
  int32_t rc;
  std::string data;
  if (!ps_request(c, kGeoInit, payload, &rc, &data)) return -1;
  return rc;
}

int pd_ps_client_geo_push(void* client, int32_t trainer_id,
                          const int64_t* keys, const float* deltas,
                          int64_t n) {
  auto* c = static_cast<PsClient*>(client);
  std::string payload;
  payload.append(reinterpret_cast<const char*>(&trainer_id), 4);
  payload.append(reinterpret_cast<const char*>(&n), 8);
  payload.append(reinterpret_cast<const char*>(keys), n * 8);
  payload.append(reinterpret_cast<const char*>(deltas),
                 static_cast<size_t>(n) * c->dim * 4);
  int32_t rc;
  std::string data;
  if (!ps_request(c, kGeoPush, payload, &rc, &data)) return -1;
  return rc;
}

int64_t pd_ps_client_geo_pull_count(void* client, int32_t trainer_id) {
  auto* c = static_cast<PsClient*>(client);
  std::string payload(reinterpret_cast<const char*>(&trainer_id), 4);
  int32_t rc;
  std::string data;
  if (!ps_request(c, kGeoPullCount, payload, &rc, &data) || rc != 0 ||
      data.size() != 8)
    return -1;
  int64_t queued;
  memcpy(&queued, data.data(), 8);
  return queued;
}

int64_t pd_ps_client_geo_pull(void* client, int32_t trainer_id,
                              int64_t* keys_out, float* vals_out,
                              int64_t max_n) {
  auto* c = static_cast<PsClient*>(client);
  std::string payload;
  payload.append(reinterpret_cast<const char*>(&trainer_id), 4);
  payload.append(reinterpret_cast<const char*>(&max_n), 8);
  int32_t rc;
  std::string data;
  if (!ps_request(c, kGeoPull, payload, &rc, &data) || rc != 0)
    return -1;
  if (data.size() < 8) return -1;
  int64_t got;
  memcpy(&got, data.data(), 8);
  if (got < 0 || data.size() !=
      8 + static_cast<size_t>(got) * (8 + c->dim * 4)) return -1;
  memcpy(keys_out, data.data() + 8, got * 8);
  memcpy(vals_out, data.data() + 8 + got * 8,
         static_cast<size_t>(got) * c->dim * 4);
  return got;
}

int pd_ps_client_push_show_click(void* client, const int64_t* keys,
                                 const float* shows, const float* clicks,
                                 int64_t n) {
  auto* c = static_cast<PsClient*>(client);
  std::string payload;
  payload.append(reinterpret_cast<const char*>(&n), 8);
  payload.append(reinterpret_cast<const char*>(keys), n * 8);
  payload.append(reinterpret_cast<const char*>(shows), n * 4);
  payload.append(reinterpret_cast<const char*>(clicks), n * 4);
  int32_t rc;
  std::string data;
  if (!ps_request(c, kShowClick, payload, &rc, &data)) return -1;
  return rc;
}

int64_t pd_ps_client_shrink(void* client) {
  auto* c = static_cast<PsClient*>(client);
  int32_t rc;
  std::string data;
  if (!ps_request(c, kShrink, "", &rc, &data) || rc != 0 ||
      data.size() != 8)
    return -1;
  int64_t evicted;
  memcpy(&evicted, data.data(), 8);
  return evicted;
}

int pd_ps_client_stats(void* client, int64_t* mem_rows, int64_t* disk_rows) {
  auto* c = static_cast<PsClient*>(client);
  int32_t rc;
  std::string data;
  if (!ps_request(c, kStats, "", &rc, &data) || rc != 0 ||
      data.size() != 16)
    return -1;
  memcpy(mem_rows, data.data(), 8);
  memcpy(disk_rows, data.data() + 8, 8);
  return 0;
}

int pd_ps_client_save(void* client, const char* path) {
  auto* c = static_cast<PsClient*>(client);
  int32_t rc;
  std::string data;
  if (!ps_request(c, kSave, path, &rc, &data)) return -1;
  return rc;
}

int pd_ps_client_load(void* client, const char* path) {
  auto* c = static_cast<PsClient*>(client);
  int32_t rc;
  std::string data;
  if (!ps_request(c, kLoad, path, &rc, &data)) return -1;
  return rc;
}

int pd_ps_client_graph_add_edges(void* client, const int64_t* src,
                                 const int64_t* dst, const float* weights,
                                 int64_t n) {
  auto* c = static_cast<PsClient*>(client);
  std::string payload;
  payload.append(reinterpret_cast<const char*>(&n), 8);
  payload.push_back(weights ? 1 : 0);
  payload.append(reinterpret_cast<const char*>(src), n * 8);
  payload.append(reinterpret_cast<const char*>(dst), n * 8);
  if (weights)
    payload.append(reinterpret_cast<const char*>(weights), n * 4);
  int32_t rc;
  std::string data;
  if (!ps_request(c, kGraphAddEdges, payload, &rc, &data)) return -1;
  return rc;
}

int pd_ps_client_graph_sample(void* client, const int64_t* nodes, int64_t n,
                              int k, int64_t* out_nbrs,
                              int64_t* out_counts) {
  auto* c = static_cast<PsClient*>(client);
  std::string payload;
  int32_t kk = k;
  payload.append(reinterpret_cast<const char*>(&n), 8);
  payload.append(reinterpret_cast<const char*>(&kk), 4);
  payload.append(reinterpret_cast<const char*>(nodes), n * 8);
  int32_t rc;
  std::string data;
  if (!ps_request(c, kGraphSample, payload, &rc, &data)) return -1;
  if (rc != 0) return rc;
  if (data.size() != static_cast<size_t>(n) * (k + 1) * 8) return -4;
  memcpy(out_nbrs, data.data(), static_cast<size_t>(n) * k * 8);
  memcpy(out_counts, data.data() + static_cast<size_t>(n) * k * 8, n * 8);
  return 0;
}

int pd_ps_client_graph_degrees(void* client, const int64_t* nodes,
                               int64_t n, int64_t* out) {
  auto* c = static_cast<PsClient*>(client);
  std::string payload;
  payload.append(reinterpret_cast<const char*>(&n), 8);
  payload.append(reinterpret_cast<const char*>(nodes), n * 8);
  int32_t rc;
  std::string data;
  if (!ps_request(c, kGraphDegrees, payload, &rc, &data)) return -1;
  if (rc != 0) return rc;
  if (data.size() != static_cast<size_t>(n) * 8) return -4;
  memcpy(out, data.data(), data.size());
  return 0;
}

int pd_ps_client_graph_size(void* client, int64_t* num_nodes,
                            int64_t* num_edges) {
  auto* c = static_cast<PsClient*>(client);
  int32_t rc;
  std::string data;
  if (!ps_request(c, kGraphSize, "", &rc, &data) || rc != 0 ||
      data.size() != 16)
    return -1;
  memcpy(num_nodes, data.data(), 8);
  memcpy(num_edges, data.data() + 8, 8);
  return 0;
}

int pd_ps_client_graph_save(void* client, const char* path) {
  auto* c = static_cast<PsClient*>(client);
  int32_t rc;
  std::string data;
  if (!ps_request(c, kGraphSave, path, &rc, &data)) return -1;
  return rc;
}

int pd_ps_client_graph_load(void* client, const char* path) {
  auto* c = static_cast<PsClient*>(client);
  int32_t rc;
  std::string data;
  if (!ps_request(c, kGraphLoad, path, &rc, &data)) return -1;
  return rc;
}

char* pd_ps_last_error(void) {
  char* out = static_cast<char*>(malloc(g_ps_error.size() + 1));
  memcpy(out, g_ps_error.c_str(), g_ps_error.size() + 1);
  return out;
}

}  // extern "C"
