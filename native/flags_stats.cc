// Native flag registry + memory stat counters.
//
// Flag registry: parity with the reference's exported-gflags surface
// (paddle/phi/core/flags.cc — PHI_DEFINE_EXPORTED_*, paddle.set_flags /
// get_flags); here a mutex-guarded string map seeded from FLAGS_* env vars on
// first touch, shared by every in-process consumer (Python layer mirrors it).
//
// Memory stats: parity with paddle/fluid/memory/stats.cc —
// Stat{Update,GetCurrent,GetPeak} keyed by (kind, device id) with a
// lock-free peak update. On TPU, device memory is owned by PjRt/XLA, so these
// track host-side accounting and whatever the Python layer reports from
// device allocation stats.
#include "paddle_native.h"

#include <atomic>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <string>

namespace {

std::mutex g_flags_mu;
std::map<std::string, std::string>& flag_map() {
  static std::map<std::string, std::string> m;
  return m;
}

struct StatSlot {
  std::atomic<int64_t> current{0};
  std::atomic<int64_t> peak{0};
};

std::mutex g_stats_mu;
std::map<std::string, StatSlot*>& stat_map() {
  static std::map<std::string, StatSlot*> m;
  return m;
}

StatSlot* slot(const char* kind, int dev_id) {
  std::string key = std::string(kind) + "#" + std::to_string(dev_id);
  std::lock_guard<std::mutex> lk(g_stats_mu);
  auto& m = stat_map();
  auto it = m.find(key);
  if (it == m.end()) it = m.emplace(key, new StatSlot).first;
  return it->second;
}

char* dup_cstr(const std::string& s) {
  char* out = static_cast<char*>(malloc(s.size() + 1));
  memcpy(out, s.c_str(), s.size() + 1);
  return out;
}

}  // namespace

extern "C" {

int pd_flags_set(const char* name, const char* value) {
  std::lock_guard<std::mutex> lk(g_flags_mu);
  flag_map()[name] = value ? value : "";
  return 0;
}

char* pd_flags_get(const char* name) {
  {
    std::lock_guard<std::mutex> lk(g_flags_mu);
    auto& m = flag_map();
    auto it = m.find(name);
    if (it != m.end()) return dup_cstr(it->second);
  }
  const char* env = getenv(name);
  if (!env) return nullptr;
  std::lock_guard<std::mutex> lk(g_flags_mu);
  flag_map()[name] = env;
  return dup_cstr(env);
}

char* pd_flags_dump(void) {
  std::lock_guard<std::mutex> lk(g_flags_mu);
  std::string out;
  for (auto& kv : flag_map()) {
    out += kv.first;
    out += "=";
    out += kv.second;
    out += "\n";
  }
  return dup_cstr(out);
}

void pd_stat_update(const char* kind, int dev_id, int64_t delta) {
  StatSlot* s = slot(kind, dev_id);
  int64_t cur = s->current.fetch_add(delta) + delta;
  int64_t prev = s->peak.load();
  while (cur > prev && !s->peak.compare_exchange_weak(prev, cur)) {}
}

int64_t pd_stat_current(const char* kind, int dev_id) {
  return slot(kind, dev_id)->current.load();
}

int64_t pd_stat_peak(const char* kind, int dev_id) {
  return slot(kind, dev_id)->peak.load();
}

void pd_stat_reset_peak(const char* kind, int dev_id) {
  StatSlot* s = slot(kind, dev_id);
  s->peak.store(s->current.load());
}

}  // extern "C"
