// Inference C API — native client for the predictor server.
//
// Role parity with the reference C inference API
// (paddle/fluid/inference/capi_exp/pd_inference_api.h): C/C++/Go programs
// create a predictor handle, feed tensors, run, and fetch outputs.  The
// compute engine here is the Python/XLA runtime, so the handle wraps a
// TCP connection to a PredictorServer (paddle_tpu/inference/serving.py)
// instead of an in-process C++ engine; the tensor wire format is the
// length-prefixed encoding documented in serving.py.
#include "paddle_native.h"

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <stdio.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdlib>
#include <string>
#include <vector>

namespace {

thread_local std::string g_infer_error;

struct InferTensor {
  uint8_t dtype;
  std::vector<uint64_t> dims;
  std::vector<uint8_t> data;
};

struct InferClient {
  int fd = -1;
  int timeout_ms = 60000;
  std::vector<InferTensor> inputs;
  std::vector<InferTensor> outputs;
};

size_t dtype_size(uint8_t code) {
  switch (code) {
    case 0: return 4;  // f32
    case 1: return 8;  // f64
    case 2: return 4;  // i32
    case 3: return 8;  // i64
    case 4: return 1;  // u8
    case 5: return 1;  // bool
  }
  return 0;
}

bool send_all(int fd, const void* p, size_t n) {
  const char* c = static_cast<const char*>(p);
  while (n) {
    ssize_t w = send(fd, c, n, MSG_NOSIGNAL);
    if (w < 0) {
      if (errno == EINTR) continue;
      g_infer_error = std::string("send: ") + strerror(errno);
      return false;
    }
    c += w;
    n -= w;
  }
  return true;
}

bool recv_all(int fd, void* p, size_t n, int timeout_ms) {
  char* c = static_cast<char*>(p);
  while (n) {
    pollfd pfd{fd, POLLIN, 0};
    int r = poll(&pfd, 1, timeout_ms);
    if (r == 0) { g_infer_error = "infer recv timeout"; return false; }
    if (r < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    ssize_t got = recv(fd, c, n, 0);
    if (got <= 0) {
      if (got < 0 && errno == EINTR) continue;
      g_infer_error = "infer server closed connection";
      return false;
    }
    c += got;
    n -= got;
  }
  return true;
}

}  // namespace

extern "C" {

void* pd_infer_connect(const char* host, int port, int timeout_ms) {
  addrinfo hints{}, *res = nullptr;
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  char portstr[16];
  snprintf(portstr, sizeof portstr, "%d", port);
  if (getaddrinfo(host, portstr, &hints, &res) != 0 || !res) {
    g_infer_error = std::string("getaddrinfo failed for ") + host;
    return nullptr;
  }
  int fd = socket(res->ai_family, res->ai_socktype, res->ai_protocol);
  if (fd < 0 || connect(fd, res->ai_addr, res->ai_addrlen) != 0) {
    g_infer_error = std::string("connect failed: ") + strerror(errno);
    if (fd >= 0) close(fd);
    freeaddrinfo(res);
    return nullptr;
  }
  freeaddrinfo(res);
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
  auto* c = new InferClient;
  c->fd = fd;
  if (timeout_ms > 0) c->timeout_ms = timeout_ms;
  return c;
}

void pd_infer_close(void* client) {
  if (!client) return;
  auto* c = static_cast<InferClient*>(client);
  if (c->fd >= 0) close(c->fd);
  delete c;
}

// Stage one input tensor (copied). dtype codes as in serving.py.
// Returns -3 for an unknown dtype code.
int pd_infer_add_input(void* client, int dtype, const int64_t* dims,
                       int ndim, const void* data) {
  auto* c = static_cast<InferClient*>(client);
  if (dtype_size(static_cast<uint8_t>(dtype)) == 0) {
    g_infer_error = "unknown dtype code";
    return -3;
  }
  InferTensor t;
  t.dtype = static_cast<uint8_t>(dtype);
  size_t elems = 1;
  for (int i = 0; i < ndim; ++i) {
    t.dims.push_back(static_cast<uint64_t>(dims[i]));
    elems *= static_cast<size_t>(dims[i]);
  }
  size_t bytes = elems * dtype_size(t.dtype);
  t.data.assign(static_cast<const uint8_t*>(data),
                static_cast<const uint8_t*>(data) + bytes);
  c->inputs.push_back(std::move(t));
  return 0;
}

namespace {
// A failed/timed-out exchange leaves the stream desynced: poison the
// connection so a retry errors loudly instead of parsing stale bytes.
int poison_client(InferClient* c) {
  if (c->fd >= 0) close(c->fd);
  c->fd = -1;
  return -1;
}
}  // namespace

// Run: sends staged inputs, receives outputs. Returns 0 ok, -1 transport
// error (connection poisoned; reconnect), -2 remote error (message via
// pd_infer_last_error; connection still usable).
int pd_infer_run(void* client) {
  auto* c = static_cast<InferClient*>(client);
  if (c->fd < 0) {
    g_infer_error = "connection previously failed; reconnect";
    return -1;
  }
  c->outputs.clear();
  uint32_t n = static_cast<uint32_t>(c->inputs.size());
  if (!send_all(c->fd, &n, 4)) return poison_client(c);
  for (auto& t : c->inputs) {
    uint8_t hdr[2] = {t.dtype, static_cast<uint8_t>(t.dims.size())};
    if (!send_all(c->fd, hdr, 2)) return poison_client(c);
    if (!t.dims.empty() &&
        !send_all(c->fd, t.dims.data(), t.dims.size() * 8))
      return poison_client(c);
    if (!send_all(c->fd, t.data.data(), t.data.size()))
      return poison_client(c);
  }
  c->inputs.clear();
  uint8_t status;
  if (!recv_all(c->fd, &status, 1, c->timeout_ms)) return poison_client(c);
  uint32_t count;
  if (!recv_all(c->fd, &count, 4, c->timeout_ms)) return poison_client(c);
  if (status != 0) {
    std::string msg(count, '\0');
    if (count && !recv_all(c->fd, &msg[0], count, c->timeout_ms))
      return poison_client(c);
    g_infer_error = "remote: " + msg;
    return -2;
  }
  for (uint32_t i = 0; i < count; ++i) {
    uint8_t hdr[2];
    if (!recv_all(c->fd, hdr, 2, c->timeout_ms)) return poison_client(c);
    InferTensor t;
    t.dtype = hdr[0];
    if (dtype_size(t.dtype) == 0) {
      g_infer_error = "server sent unknown dtype code";
      return poison_client(c);
    }
    t.dims.resize(hdr[1]);
    if (hdr[1] &&
        !recv_all(c->fd, t.dims.data(), t.dims.size() * 8, c->timeout_ms))
      return poison_client(c);
    size_t elems = 1;
    for (auto d : t.dims) elems *= d;
    t.data.resize(elems * dtype_size(t.dtype));
    if (!t.data.empty() &&
        !recv_all(c->fd, t.data.data(), t.data.size(), c->timeout_ms))
      return poison_client(c);
    c->outputs.push_back(std::move(t));
  }
  return 0;
}

int pd_infer_num_outputs(void* client) {
  return static_cast<int>(static_cast<InferClient*>(client)->outputs.size());
}

// Output metadata; dims buffer must hold >= 8 entries. Returns ndim or -1.
int pd_infer_output_dims(void* client, int index, int* dtype,
                         int64_t* dims) {
  auto* c = static_cast<InferClient*>(client);
  if (index < 0 || index >= static_cast<int>(c->outputs.size())) return -1;
  auto& t = c->outputs[index];
  *dtype = t.dtype;
  for (size_t i = 0; i < t.dims.size() && i < 8; ++i)
    dims[i] = static_cast<int64_t>(t.dims[i]);
  return static_cast<int>(t.dims.size());
}

// Copy output payload into caller buffer of byte size buf_len.
int pd_infer_output_data(void* client, int index, void* buf,
                         int64_t buf_len) {
  auto* c = static_cast<InferClient*>(client);
  if (index < 0 || index >= static_cast<int>(c->outputs.size())) return -1;
  auto& t = c->outputs[index];
  if (buf_len < static_cast<int64_t>(t.data.size())) return -2;
  memcpy(buf, t.data.data(), t.data.size());
  return 0;
}

char* pd_infer_last_error(void) {
  char* out = static_cast<char*>(malloc(g_infer_error.size() + 1));
  memcpy(out, g_infer_error.c_str(), g_infer_error.size() + 1);
  return out;
}

}  // extern "C"
