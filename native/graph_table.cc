// Graph table — server-side graph storage + neighbor sampling.
//
// Role parity with the reference GraphPS
// (paddle/fluid/distributed/ps/table/common_graph_table.h: add_graph_node /
// add edges, random_sample_neighbors with optional edge weights, served
// over brpc).  Design here is new: per-node adjacency vectors in sharded
// hash maps (same sharding/locking scheme as sparse_table.cc), weighted
// sampling without replacement via the exponential-sort trick
// (key = -log(u)/w, take the k smallest), deterministic from a per-call
// splitmix64 stream so distributed runs reproduce.
#include "paddle_native.h"

#include <math.h>
#include <stdio.h>
#include <string.h>

#include <algorithm>
#include <atomic>
#include <mutex>
#include <unordered_map>
#include <vector>

namespace {

constexpr int kGraphShards = 16;

struct Adj {
  std::vector<int64_t> nbrs;
  std::vector<float> weights;  // empty = unweighted
};

struct Graph {
  uint64_t seed;
  uint64_t sample_counter = 0;
  // updated under DIFFERENT per-shard locks concurrently: must be atomic
  std::atomic<int64_t> num_edges{0};
  std::unordered_map<int64_t, Adj> shards[kGraphShards];
  std::mutex locks[kGraphShards];
};

inline int gshard(int64_t key) {
  return static_cast<uint64_t>(key) % kGraphShards;
}

inline uint64_t mix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ull;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
  return x ^ (x >> 31);
}

inline double u01(uint64_t state) {
  return ((state >> 11) + 1.0) * (1.0 / 9007199254740993.0);  // (0,1)
}

}  // namespace

extern "C" {

void* pd_graph_create(uint64_t seed) {
  auto* g = new Graph;
  g->seed = seed;
  return g;
}

void pd_graph_destroy(void* graph) { delete static_cast<Graph*>(graph); }

// directed edges src->dst; weights may be NULL (uniform sampling)
void pd_graph_add_edges(void* graph, const int64_t* src, const int64_t* dst,
                        const float* weights, int64_t n) {
  auto* g = static_cast<Graph*>(graph);
  for (int64_t i = 0; i < n; ++i) {
    int s = gshard(src[i]);
    std::lock_guard<std::mutex> lk(g->locks[s]);
    Adj& a = g->shards[s][src[i]];
    a.nbrs.push_back(dst[i]);
    if (weights) {
      if (a.weights.size() != a.nbrs.size() - 1)
        a.weights.resize(a.nbrs.size() - 1, 1.0f);  // mixed: backfill 1.0
      a.weights.push_back(weights[i]);
    } else if (!a.weights.empty()) {
      a.weights.push_back(1.0f);
    }
    g->num_edges.fetch_add(1, std::memory_order_relaxed);
  }
}

int64_t pd_graph_num_nodes(void* graph) {
  auto* g = static_cast<Graph*>(graph);
  int64_t n = 0;
  for (int s = 0; s < kGraphShards; ++s) {
    std::lock_guard<std::mutex> lk(g->locks[s]);
    n += static_cast<int64_t>(g->shards[s].size());
  }
  return n;
}

int64_t pd_graph_num_edges(void* graph) {
  return static_cast<Graph*>(graph)->num_edges.load();
}

void pd_graph_degrees(void* graph, const int64_t* nodes, int64_t n,
                      int64_t* out) {
  auto* g = static_cast<Graph*>(graph);
  for (int64_t i = 0; i < n; ++i) {
    int s = gshard(nodes[i]);
    std::lock_guard<std::mutex> lk(g->locks[s]);
    auto it = g->shards[s].find(nodes[i]);
    out[i] = it == g->shards[s].end()
                 ? 0
                 : static_cast<int64_t>(it->second.nbrs.size());
  }
}

// Sample up to k neighbors per node WITHOUT replacement (weighted when
// edge weights exist).  out_nbrs [n*k] padded with -1; out_counts [n].
// Deterministic in (graph seed, per-table sample counter, node id).
void pd_graph_sample_neighbors(void* graph, const int64_t* nodes, int64_t n,
                               int k, int64_t* out_nbrs,
                               int64_t* out_counts) {
  auto* g = static_cast<Graph*>(graph);
  uint64_t call = __atomic_fetch_add(&g->sample_counter, 1, __ATOMIC_RELAXED);
  for (int64_t i = 0; i < n * k; ++i) out_nbrs[i] = -1;
  for (int64_t i = 0; i < n; ++i) {
    int s = gshard(nodes[i]);
    std::lock_guard<std::mutex> lk(g->locks[s]);
    auto it = g->shards[s].find(nodes[i]);
    if (it == g->shards[s].end()) {
      out_counts[i] = 0;
      continue;
    }
    const Adj& a = it->second;
    int64_t deg = static_cast<int64_t>(a.nbrs.size());
    if (deg <= k) {
      for (int64_t j = 0; j < deg; ++j) out_nbrs[i * k + j] = a.nbrs[j];
      out_counts[i] = deg;
      continue;
    }
    // exponential-sort weighted sampling without replacement:
    // key_j = -log(u_j) / w_j; the k SMALLEST keys win
    std::vector<std::pair<double, int64_t>> keys(deg);
    uint64_t base = mix64(g->seed ^ mix64(call) ^
                          static_cast<uint64_t>(nodes[i]));
    for (int64_t j = 0; j < deg; ++j) {
      base = mix64(base);
      double w = a.weights.empty() ? 1.0
                                   : std::max(1e-12f, a.weights[j]);
      keys[j] = {-log(u01(base)) / w, a.nbrs[j]};
    }
    std::nth_element(keys.begin(), keys.begin() + k, keys.end());
    for (int j = 0; j < k; ++j) out_nbrs[i * k + j] = keys[j].second;
    out_counts[i] = k;
  }
}

// Binary format: magic "PDG1" | i64 node_count | per node:
//   i64 id | i64 degree | u8 weighted | i64 nbrs[deg] | [f32 w[deg]]
int pd_graph_save(void* graph, const char* path) {
  auto* g = static_cast<Graph*>(graph);
  FILE* f = fopen(path, "wb");
  if (!f) return -1;
  const char magic[4] = {'P', 'D', 'G', '1'};
  fwrite(magic, 1, 4, f);
  int64_t count = 0;
  long pos = ftell(f);
  fwrite(&count, 8, 1, f);
  for (int s = 0; s < kGraphShards; ++s) {
    std::lock_guard<std::mutex> lk(g->locks[s]);
    for (auto& kv : g->shards[s]) {
      int64_t deg = static_cast<int64_t>(kv.second.nbrs.size());
      uint8_t weighted = kv.second.weights.empty() ? 0 : 1;
      fwrite(&kv.first, 8, 1, f);
      fwrite(&deg, 8, 1, f);
      fwrite(&weighted, 1, 1, f);
      fwrite(kv.second.nbrs.data(), 8, deg, f);
      if (weighted) fwrite(kv.second.weights.data(), 4, deg, f);
      ++count;
    }
  }
  if (fseek(f, pos, SEEK_SET) != 0 || fwrite(&count, 8, 1, f) != 1) {
    fclose(f);
    return -4;
  }
  if (fclose(f) != 0) return -5;
  return 0;
}

int pd_graph_load(void* graph, const char* path) {
  auto* g = static_cast<Graph*>(graph);
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  char magic[4];
  int64_t count;
  if (fread(magic, 1, 4, f) != 4 || memcmp(magic, "PDG1", 4) != 0 ||
      fread(&count, 8, 1, f) != 1) {
    fclose(f);
    return -2;
  }
  // degree sanity bound: a corrupt file must return rc=-3, not throw
  // bad_alloc through the C ABI (which would terminate the process)
  constexpr int64_t kMaxDegree = 1ll << 31;
  for (int64_t i = 0; i < count; ++i) {
    int64_t id, deg;
    uint8_t weighted;
    if (fread(&id, 8, 1, f) != 1 || fread(&deg, 8, 1, f) != 1 ||
        fread(&weighted, 1, 1, f) != 1 || deg < 0 || deg > kMaxDegree) {
      fclose(f);
      return -3;
    }
    Adj a;
    try {
      a.nbrs.resize(deg);
      if (weighted) a.weights.resize(deg);
    } catch (const std::exception&) {
      fclose(f);
      return -3;
    }
    if (fread(a.nbrs.data(), 8, deg, f) != static_cast<size_t>(deg)) {
      fclose(f);
      return -3;
    }
    if (weighted &&
        fread(a.weights.data(), 4, deg, f) != static_cast<size_t>(deg)) {
      fclose(f);
      return -3;
    }
    int s = gshard(id);
    std::lock_guard<std::mutex> lk(g->locks[s]);
    g->num_edges.fetch_add(
        deg - static_cast<int64_t>(g->shards[s][id].nbrs.size()),
        std::memory_order_relaxed);
    g->shards[s][id] = std::move(a);
  }
  fclose(f);
  return 0;
}

}  // extern "C"
