// paddle_tpu native runtime core — C ABI surface.
//
// TPU-native equivalents of the reference's host-side C++ runtime pieces:
//   * TCPStore rendezvous KV   (ref: paddle/phi/core/distributed/store/tcp_store.h:120)
//   * exported flag registry   (ref: paddle/phi/core/flags.cc)
//   * host/device memory stats (ref: paddle/fluid/memory/stats.cc)
//   * enforce-style error stack (ref: paddle/fluid/platform/enforce.h)
//
// Fresh design, not a port: single poll()-driven server thread, length-prefixed
// binary frames, C ABI only (loaded from Python via ctypes — no pybind11).
#ifndef PADDLE_NATIVE_H_
#define PADDLE_NATIVE_H_

#include <stdint.h>
#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

// ---------------------------------------------------------------- TCPStore --
// Server: owns the KV map; runs a background poll loop.
// Returns opaque handle (NULL on failure). port==0 picks a free port.
void* pd_store_server_start(int port);
int   pd_store_server_port(void* server);
void  pd_store_server_stop(void* server);

// Client: blocking connection to host:port.
// timeout_ms applies to connect and to every wait().
void* pd_store_client_connect(const char* host, int port, int timeout_ms);
void  pd_store_client_close(void* client);

// All return 0 on success, negative errno-style codes on failure.
int pd_store_set(void* client, const char* key, const uint8_t* val, uint64_t len);
// get: allocates *val via malloc (caller frees with pd_free). -2 == not found.
int pd_store_get(void* client, const char* key, uint8_t** val, uint64_t* len);
// add: atomic fetch-add on an int64 counter key; *out receives the new value.
int pd_store_add(void* client, const char* key, int64_t delta, int64_t* out);
// wait: block until key exists (server-side parked wait, no polling).
int pd_store_wait(void* client, const char* key, int timeout_ms);
int pd_store_del(void* client, const char* key);
int pd_store_num_keys(void* client, int64_t* out);

void pd_free(void* p);

// ------------------------------------------------------------------- Flags --
int         pd_flags_set(const char* name, const char* value);
// returns malloc'd string (pd_free) or NULL if unset.
char*       pd_flags_get(const char* name);
// newline-joined "name=value" dump; malloc'd.
char*       pd_flags_dump(void);

// ------------------------------------------------------------ Memory stats --
// Mirrors Stat{Update,GetCurrent,GetPeak} keyed by (stat_kind, dev_id).
void    pd_stat_update(const char* kind, int dev_id, int64_t delta);
int64_t pd_stat_current(const char* kind, int dev_id);
int64_t pd_stat_peak(const char* kind, int dev_id);
void    pd_stat_reset_peak(const char* kind, int dev_id);

// ------------------------------------------------------------ Sparse table --
// Host-resident PS embedding table (sparse_table.cc).
void*   pd_table_create(int dim, float init_range, uint64_t seed);
void    pd_table_destroy(void* table);
int     pd_table_dim(void* table);
int64_t pd_table_size(void* table);
void    pd_table_pull(void* table, const int64_t* keys, int64_t n, float* out);
void    pd_table_push_sgd(void* table, const int64_t* keys, const float* grads,
                          int64_t n, float lr);
void    pd_table_push_adagrad(void* table, const int64_t* keys,
                              const float* grads, int64_t n, float lr,
                              float eps);
int     pd_table_save(void* table, const char* path);
int     pd_table_load(void* table, const char* path);
/* CTR accessor + disk tier + GeoSGD (ctr_accessor.cc / ssd_sparse_table.h /
   memory_sparse_geo_table.h roles) */
int64_t pd_table_mem_rows(void* table);
int64_t pd_table_disk_rows(void* table);
int     pd_table_enable_disk(void* table, const char* path,
                             int64_t max_mem_rows);
void    pd_table_set_ctr(void* table, float nonclk_coeff, float click_coeff,
                         float decay_rate, float delete_threshold,
                         int delete_after_unseen_days);
void    pd_table_push_delta(void* table, const int64_t* keys,
                            const float* deltas, int64_t n);
int     pd_table_geo_init(void* table, int trainer_num);
int     pd_table_geo_push(void* table, int trainer_id,
                          const int64_t* keys, const float* deltas,
                          int64_t n);
int64_t pd_table_geo_pull_count(void* table, int trainer_id);
int64_t pd_table_geo_pull(void* table, int trainer_id, int64_t* keys_out,
                          float* vals_out, int64_t max_n);
void    pd_table_push_show_click(void* table, const int64_t* keys,
                                 const float* shows, const float* clicks,
                                 int64_t n);
void    pd_table_get_meta(void* table, const int64_t* keys, int64_t n,
                          float* out);
int64_t pd_table_shrink(void* table);
int     pd_ps_client_push_delta(void* client, const int64_t* keys,
                                const float* deltas, int64_t n);
int     pd_ps_client_geo_init(void* client, int32_t trainer_num);
int     pd_ps_client_geo_push(void* client, int32_t trainer_id,
                              const int64_t* keys, const float* deltas,
                              int64_t n);
int64_t pd_ps_client_geo_pull_count(void* client, int32_t trainer_id);
int64_t pd_ps_client_geo_pull(void* client, int32_t trainer_id,
                              int64_t* keys_out, float* vals_out,
                              int64_t max_n);
int     pd_ps_client_push_show_click(void* client, const int64_t* keys,
                                     const float* shows, const float* clicks,
                                     int64_t n);
int64_t pd_ps_client_shrink(void* client);
int     pd_ps_client_stats(void* client, int64_t* mem_rows,
                           int64_t* disk_rows);
/* Graph table (GraphPS role: common_graph_table.h + graph brpc service) */
void*   pd_graph_create(uint64_t seed);
void    pd_graph_destroy(void* graph);
void    pd_graph_add_edges(void* graph, const int64_t* src,
                           const int64_t* dst, const float* weights,
                           int64_t n);
int64_t pd_graph_num_nodes(void* graph);
int64_t pd_graph_num_edges(void* graph);
void    pd_graph_degrees(void* graph, const int64_t* nodes, int64_t n,
                         int64_t* out);
void    pd_graph_sample_neighbors(void* graph, const int64_t* nodes,
                                  int64_t n, int k, int64_t* out_nbrs,
                                  int64_t* out_counts);
int     pd_graph_save(void* graph, const char* path);
int     pd_graph_load(void* graph, const char* path);
void*   pd_ps_graph_server_start(void* graph, int port);
int     pd_ps_client_graph_add_edges(void* client, const int64_t* src,
                                     const int64_t* dst,
                                     const float* weights, int64_t n);
int     pd_ps_client_graph_sample(void* client, const int64_t* nodes,
                                  int64_t n, int k, int64_t* out_nbrs,
                                  int64_t* out_counts);
int     pd_ps_client_graph_degrees(void* client, const int64_t* nodes,
                                   int64_t n, int64_t* out);
int     pd_ps_client_graph_size(void* client, int64_t* num_nodes,
                                int64_t* num_edges);
int     pd_ps_client_graph_save(void* client, const char* path);
int     pd_ps_client_graph_load(void* client, const char* path);

// ------------------------------------------------------------- PS service --
// Multi-host PS data plane (ps_service.cc): serve a table over TCP; clients
// shard keys across servers (key % num_servers) in the Python layer.
// Server borrows the table handle; stop the server before destroying it.
void*   pd_ps_server_start(void* table, int port);
int     pd_ps_server_port(void* server);
void    pd_ps_server_stop(void* server);
void*   pd_ps_client_connect(const char* host, int port, int timeout_ms);
void    pd_ps_client_close(void* client);
int     pd_ps_client_dim(void* client);
int64_t pd_ps_client_size(void* client);
int     pd_ps_client_pull(void* client, const int64_t* keys, int64_t n,
                          float* out);
int     pd_ps_client_push(void* client, int opt, const int64_t* keys,
                          const float* grads, int64_t n, float lr, float eps);
int     pd_ps_client_save(void* client, const char* path);
int     pd_ps_client_load(void* client, const char* path);
char*   pd_ps_last_error(void);

// ------------------------------------------------------------ Inference C --
// C inference API (infer_client.cc): connect to a PredictorServer
// (paddle_tpu/inference/serving.py) and run tensors through it.
// dtype codes: 0=f32 1=f64 2=i32 3=i64 4=u8 5=bool.
void* pd_infer_connect(const char* host, int port, int timeout_ms);
void  pd_infer_close(void* client);
int   pd_infer_add_input(void* client, int dtype, const int64_t* dims,
                         int ndim, const void* data);
int   pd_infer_run(void* client);
int   pd_infer_num_outputs(void* client);
int   pd_infer_output_dims(void* client, int index, int* dtype,
                           int64_t* dims);
int   pd_infer_output_data(void* client, int index, void* buf,
                           int64_t buf_len);
char* pd_infer_last_error(void);

// ------------------------------------------------------------------ Errors --
// Thread-local last-error string for all pd_* calls; malloc'd copy.
char* pd_last_error(void);

#ifdef __cplusplus
}  // extern "C"
#endif

#endif  // PADDLE_NATIVE_H_
