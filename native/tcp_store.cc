// TCPStore — rendezvous key-value store for multi-host bootstrap.
//
// Role parity with the reference's master-hosted KV store
// (paddle/phi/core/distributed/store/tcp_store.h:120, tcp_utils.cc): rank 0
// hosts the map; all ranks set/get/add/wait to coordinate mesh bootstrap and
// barriers over DCN.  The design here is new: one poll(2) loop services all
// connections with non-blocking sockets and per-connection reassembly
// buffers, and wait() parks server-side (a deferred-reply list flushed after
// every mutation) instead of client polling.
//
// Wire format (little-endian):
//   request : u8 opcode | u32 klen | key bytes | payload
//     SET  payload: u64 vlen | value bytes
//     ADD  payload: i64 delta
//     GET/WAIT/DEL/NUMKEYS payload: none
//   response: u8 status(0 ok, 1 not-found) | u64 vlen | value bytes
#include "paddle_native.h"

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

enum Op : uint8_t { kSet = 1, kGet = 2, kAdd = 3, kWait = 4, kDel = 5, kNumKeys = 6 };
enum Status : uint8_t { kOk = 0, kNotFound = 1 };

thread_local std::string g_last_error;

void set_error(const std::string& msg) { g_last_error = msg; }

struct Conn {
  int fd;
  std::string inbuf;   // partially received request bytes
  std::string outbuf;  // pending response bytes not yet flushed
  bool parked = false; // blocked in WAIT
  std::string wait_key;
};

struct Server {
  int listen_fd = -1;
  int port = 0;
  int wake_r = -1, wake_w = -1;  // self-pipe to interrupt poll on stop
  std::thread loop;
  std::atomic<bool> stopping{false};
  std::unordered_map<std::string, std::string> kv;
  std::vector<Conn*> conns;
};

void append_u32(std::string* s, uint32_t v) { s->append(reinterpret_cast<char*>(&v), 4); }
void append_u64(std::string* s, uint64_t v) { s->append(reinterpret_cast<char*>(&v), 8); }

void reply_value(Conn* c, uint8_t status, const void* data, uint64_t len) {
  c->outbuf.push_back(static_cast<char>(status));
  append_u64(&c->outbuf, len);
  if (len) c->outbuf.append(reinterpret_cast<const char*>(data), len);
}

// Flush parked WAITs whose key now exists.
void flush_waiters(Server* s) {
  for (Conn* c : s->conns) {
    if (c->parked && s->kv.count(c->wait_key)) {
      c->parked = false;
      reply_value(c, kOk, nullptr, 0);
    }
  }
}

// Try to consume one complete request from c->inbuf. Returns false if more
// bytes are needed.
constexpr uint32_t kMaxKeyLen = 1u << 16;        // 64 KiB keys
constexpr uint64_t kMaxValueLen = 1ull << 30;    // 1 GiB values

// Returns true when a full request was consumed.  A frame whose lengths
// exceed the sanity caps (corrupt stream / port scanner) marks the
// connection dead instead of letting `need` wrap size_t.
bool handle_one(Server* s, Conn* c) {
  const std::string& b = c->inbuf;
  if (b.size() < 5) return false;
  uint8_t op = static_cast<uint8_t>(b[0]);
  uint32_t klen;
  memcpy(&klen, b.data() + 1, 4);
  if (klen > kMaxKeyLen) {
    close(c->fd);
    c->fd = -1;
    return false;
  }
  size_t need = 5 + klen;
  uint64_t vlen = 0;
  if (op == kSet) {
    if (b.size() < need + 8) return false;
    memcpy(&vlen, b.data() + need, 8);
    if (vlen > kMaxValueLen) {
      close(c->fd);
      c->fd = -1;
      return false;
    }
    need += 8 + vlen;
  } else if (op == kAdd) {
    need += 8;
  }
  if (b.size() < need) return false;

  std::string key(b.data() + 5, klen);
  switch (op) {
    case kSet: {
      s->kv[key].assign(b.data() + 5 + klen + 8, vlen);
      reply_value(c, kOk, nullptr, 0);
      flush_waiters(s);
      break;
    }
    case kGet: {
      auto it = s->kv.find(key);
      if (it == s->kv.end()) reply_value(c, kNotFound, nullptr, 0);
      else reply_value(c, kOk, it->second.data(), it->second.size());
      break;
    }
    case kAdd: {
      int64_t delta;
      memcpy(&delta, b.data() + 5 + klen, 8);
      int64_t cur = 0;
      auto it = s->kv.find(key);
      if (it != s->kv.end() && it->second.size() == 8)
        memcpy(&cur, it->second.data(), 8);
      cur += delta;
      s->kv[key].assign(reinterpret_cast<char*>(&cur), 8);
      reply_value(c, kOk, &cur, 8);
      flush_waiters(s);
      break;
    }
    case kWait: {
      if (s->kv.count(key)) reply_value(c, kOk, nullptr, 0);
      else { c->parked = true; c->wait_key = key; }
      break;
    }
    case kDel: {
      s->kv.erase(key);
      reply_value(c, kOk, nullptr, 0);
      break;
    }
    case kNumKeys: {
      int64_t n = static_cast<int64_t>(s->kv.size());
      reply_value(c, kOk, &n, 8);
      break;
    }
    default:
      reply_value(c, kNotFound, nullptr, 0);
  }
  c->inbuf.erase(0, need);
  return true;
}

void set_nonblock(int fd) { fcntl(fd, F_SETFL, fcntl(fd, F_GETFL, 0) | O_NONBLOCK); }

void server_loop(Server* s) {
  char tmp[65536];
  while (!s->stopping.load()) {
    std::vector<pollfd> pfds;
    pfds.push_back({s->listen_fd, POLLIN, 0});
    pfds.push_back({s->wake_r, POLLIN, 0});
    for (Conn* c : s->conns) {
      short ev = POLLIN;
      if (!c->outbuf.empty()) ev |= POLLOUT;
      pfds.push_back({c->fd, ev, 0});
    }
    if (poll(pfds.data(), pfds.size(), 1000) < 0) {
      if (errno == EINTR) continue;
      break;
    }
    if (pfds[1].revents & POLLIN) { (void)!read(s->wake_r, tmp, sizeof tmp); }
    // Service existing connections first; pfds was sized before any accept,
    // so only the first n_polled conns have a pollfd this round.
    size_t n_polled = pfds.size() - 2;
    for (size_t i = 0; i < n_polled; ++i) {
      Conn* c = s->conns[i];
      pollfd& p = pfds[2 + i];
      bool dead = false;
      if (p.revents & (POLLERR | POLLHUP)) dead = true;
      if (!dead && (p.revents & POLLIN)) {
        ssize_t n = recv(c->fd, tmp, sizeof tmp, 0);
        if (n <= 0) dead = (n == 0 || errno != EAGAIN);
        else {
          c->inbuf.append(tmp, n);
          while (handle_one(s, c)) {}
        }
      }
      if (!dead && (p.revents & POLLOUT) && !c->outbuf.empty()) {
        ssize_t n = send(c->fd, c->outbuf.data(), c->outbuf.size(), MSG_NOSIGNAL);
        if (n > 0) c->outbuf.erase(0, n);
        else if (n < 0 && errno != EAGAIN) dead = true;
      }
      if (dead) { close(c->fd); c->fd = -1; }
    }
    for (size_t i = 0; i < s->conns.size();) {
      if (s->conns[i]->fd < 0) { delete s->conns[i]; s->conns.erase(s->conns.begin() + i); }
      else ++i;
    }
    if (pfds[0].revents & POLLIN) {
      int fd = accept(s->listen_fd, nullptr, nullptr);
      if (fd >= 0) {
        set_nonblock(fd);
        int one = 1;
        setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
        s->conns.push_back(new Conn{fd});
      }
    }
  }
}

// --------------------------------------------------------------- client ----

struct Client {
  int fd = -1;
  int timeout_ms = 30000;
};

bool send_all(Client* c, const void* data, size_t len) {
  const char* p = static_cast<const char*>(data);
  while (len) {
    ssize_t n = send(c->fd, p, len, MSG_NOSIGNAL);
    if (n < 0) {
      if (errno == EINTR) continue;
      set_error(std::string("send: ") + strerror(errno));
      return false;
    }
    p += n;
    len -= n;
  }
  return true;
}

bool recv_all(Client* c, void* data, size_t len, int timeout_ms) {
  char* p = static_cast<char*>(data);
  while (len) {
    pollfd pfd{c->fd, POLLIN, 0};
    int r = poll(&pfd, 1, timeout_ms);
    if (r == 0) { set_error("recv timeout"); return false; }
    if (r < 0) {
      if (errno == EINTR) continue;
      set_error(std::string("poll: ") + strerror(errno));
      return false;
    }
    ssize_t n = recv(c->fd, p, len, 0);
    if (n <= 0) {
      if (n < 0 && errno == EINTR) continue;
      set_error("connection closed by store server");
      return false;
    }
    p += n;
    len -= n;
  }
  return true;
}

// Any failure mid-request leaves the stream desynchronized (e.g. a WAIT
// timeout whose reply arrives later), so the connection is poisoned: closed
// immediately, and every later call fails loudly instead of reading stale
// frames.
void poison(Client* c) {
  if (c->fd >= 0) { close(c->fd); c->fd = -1; }
}

bool request(Client* c, uint8_t op, const char* key, const std::string& payload,
             uint8_t* status, std::string* value, int timeout_ms) {
  if (c->fd < 0) {
    set_error("store connection previously failed; reconnect required");
    return false;
  }
  std::string req;
  req.push_back(static_cast<char>(op));
  append_u32(&req, static_cast<uint32_t>(strlen(key)));
  req.append(key);
  req.append(payload);
  if (!send_all(c, req.data(), req.size())) { poison(c); return false; }
  uint8_t st;
  if (!recv_all(c, &st, 1, timeout_ms)) { poison(c); return false; }
  uint64_t vlen;
  if (!recv_all(c, &vlen, 8, timeout_ms)) { poison(c); return false; }
  value->resize(vlen);
  if (vlen && !recv_all(c, &value->front(), vlen, timeout_ms)) {
    poison(c);
    return false;
  }
  *status = st;
  return true;
}

}  // namespace

extern "C" {

void* pd_store_server_start(int port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) { set_error("socket failed"); return nullptr; }
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof addr) < 0 ||
      listen(fd, 128) < 0) {
    set_error(std::string("bind/listen: ") + strerror(errno));
    close(fd);
    return nullptr;
  }
  socklen_t alen = sizeof addr;
  getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  set_nonblock(fd);
  auto* s = new Server;
  s->listen_fd = fd;
  s->port = ntohs(addr.sin_port);
  int pipefd[2];
  if (pipe(pipefd) == 0) { s->wake_r = pipefd[0]; s->wake_w = pipefd[1]; set_nonblock(s->wake_r); }
  s->loop = std::thread(server_loop, s);
  return s;
}

int pd_store_server_port(void* server) {
  return server ? static_cast<Server*>(server)->port : -1;
}

void pd_store_server_stop(void* server) {
  if (!server) return;
  auto* s = static_cast<Server*>(server);
  s->stopping.store(true);
  if (s->wake_w >= 0) { char b = 1; (void)!write(s->wake_w, &b, 1); }
  if (s->loop.joinable()) s->loop.join();
  for (Conn* c : s->conns) { close(c->fd); delete c; }
  close(s->listen_fd);
  if (s->wake_r >= 0) close(s->wake_r);
  if (s->wake_w >= 0) close(s->wake_w);
  delete s;
}

void* pd_store_client_connect(const char* host, int port, int timeout_ms) {
  addrinfo hints{}, *res = nullptr;
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  char portstr[16];
  snprintf(portstr, sizeof portstr, "%d", port);
  if (getaddrinfo(host, portstr, &hints, &res) != 0 || !res) {
    set_error(std::string("getaddrinfo failed for ") + host);
    return nullptr;
  }
  // Retry non-blocking connects until timeout — peers may start before the
  // rank-0 server — with each attempt's poll bounded by the remaining time.
  int fd = -1;
  int waited = 0;
  while (true) {
    fd = socket(res->ai_family, res->ai_socktype, res->ai_protocol);
    if (fd < 0) {
      set_error(std::string("socket: ") + strerror(errno));
      freeaddrinfo(res);
      return nullptr;
    }
    set_nonblock(fd);
    int rc = connect(fd, res->ai_addr, res->ai_addrlen);
    if (rc == 0) break;
    if (errno == EINPROGRESS) {
      pollfd pfd{fd, POLLOUT, 0};
      int remaining = timeout_ms - waited;
      int attempt_ms = remaining < 1000 ? remaining : 1000;
      int pr = poll(&pfd, 1, attempt_ms > 0 ? attempt_ms : 0);
      waited += attempt_ms;
      int err = 0;
      socklen_t elen = sizeof err;
      if (pr > 0 &&
          getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &elen) == 0 && err == 0)
        break;
    }
    close(fd);
    fd = -1;
    if (waited >= timeout_ms) {
      set_error(std::string("connect timeout to ") + host + ":" + portstr);
      freeaddrinfo(res);
      return nullptr;
    }
    usleep(200 * 1000);
    waited += 200;
  }
  // back to blocking mode for the request/response path
  fcntl(fd, F_SETFL, fcntl(fd, F_GETFL, 0) & ~O_NONBLOCK);
  freeaddrinfo(res);
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
  auto* c = new Client;
  c->fd = fd;
  c->timeout_ms = timeout_ms;
  return c;
}

void pd_store_client_close(void* client) {
  if (!client) return;
  auto* c = static_cast<Client*>(client);
  close(c->fd);
  delete c;
}

int pd_store_set(void* client, const char* key, const uint8_t* val, uint64_t len) {
  auto* c = static_cast<Client*>(client);
  std::string payload;
  append_u64(&payload, len);
  payload.append(reinterpret_cast<const char*>(val), len);
  uint8_t st;
  std::string out;
  if (!request(c, kSet, key, payload, &st, &out, c->timeout_ms)) return -1;
  return st == kOk ? 0 : -2;
}

int pd_store_get(void* client, const char* key, uint8_t** val, uint64_t* len) {
  auto* c = static_cast<Client*>(client);
  uint8_t st;
  std::string out;
  if (!request(c, kGet, key, "", &st, &out, c->timeout_ms)) return -1;
  if (st != kOk) return -2;
  *len = out.size();
  *val = static_cast<uint8_t*>(malloc(out.size() ? out.size() : 1));
  memcpy(*val, out.data(), out.size());
  return 0;
}

int pd_store_add(void* client, const char* key, int64_t delta, int64_t* out) {
  auto* c = static_cast<Client*>(client);
  std::string payload(reinterpret_cast<char*>(&delta), 8);
  uint8_t st;
  std::string resp;
  if (!request(c, kAdd, key, payload, &st, &resp, c->timeout_ms) || resp.size() != 8)
    return -1;
  memcpy(out, resp.data(), 8);
  return 0;
}

int pd_store_wait(void* client, const char* key, int timeout_ms) {
  auto* c = static_cast<Client*>(client);
  uint8_t st;
  std::string out;
  int t = timeout_ms > 0 ? timeout_ms : c->timeout_ms;
  if (!request(c, kWait, key, "", &st, &out, t)) return -1;
  return st == kOk ? 0 : -2;
}

int pd_store_del(void* client, const char* key) {
  auto* c = static_cast<Client*>(client);
  uint8_t st;
  std::string out;
  if (!request(c, kDel, key, "", &st, &out, c->timeout_ms)) return -1;
  return 0;
}

int pd_store_num_keys(void* client, int64_t* out) {
  auto* c = static_cast<Client*>(client);
  uint8_t st;
  std::string resp;
  if (!request(c, kNumKeys, "", "", &st, &resp, c->timeout_ms) || resp.size() != 8)
    return -1;
  memcpy(out, resp.data(), 8);
  return 0;
}

void pd_free(void* p) { free(p); }

char* pd_last_error(void) {
  char* out = static_cast<char*>(malloc(g_last_error.size() + 1));
  memcpy(out, g_last_error.c_str(), g_last_error.size() + 1);
  return out;
}

}  // extern "C"
