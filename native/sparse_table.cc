// Memory sparse table — the parameter-server embedding store.
//
// Role parity with the reference PS sparse tables
// (paddle/fluid/distributed/ps/table/memory_sparse_table.cc — pull/push
// with in-table optimizer accessors, save/load).  Design here is new:
// sharded open hash maps guarded by per-shard mutexes, rows initialized
// deterministically from the key (splitmix64 -> uniform), and the optimizer
// (SGD / Adagrad) applied inside the push so the host owns optimizer state
// for 100B-feature-scale embeddings while the TPU only sees dense pulled
// rows.
#include "paddle_native.h"

#include <math.h>
#include <stdio.h>
#include <string.h>

#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

constexpr int kNumShards = 16;

struct Row {
  std::vector<float> w;    // embedding weights [dim]
  std::vector<float> g2;   // adagrad accumulator [dim] (lazily allocated)
};

struct Table {
  int dim;
  uint64_t seed;
  float init_range;
  std::unordered_map<int64_t, Row> shards[kNumShards];
  std::mutex locks[kNumShards];
};

inline int shard_of(int64_t key) {
  return static_cast<uint64_t>(key) % kNumShards;
}

inline uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ull;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
  return x ^ (x >> 31);
}

// deterministic per-(key, slot) uniform init in [-range, range]
void init_row(Table* t, int64_t key, Row* row) {
  row->w.resize(t->dim);
  uint64_t state = splitmix64(static_cast<uint64_t>(key) ^ t->seed);
  for (int i = 0; i < t->dim; ++i) {
    state = splitmix64(state);
    double u = (state >> 11) * (1.0 / 9007199254740992.0);  // [0,1)
    row->w[i] = static_cast<float>((2.0 * u - 1.0) * t->init_range);
  }
}

Row* find_or_create(Table* t, int64_t key) {
  int s = shard_of(key);
  auto& m = t->shards[s];
  auto it = m.find(key);
  if (it == m.end()) {
    it = m.emplace(key, Row{}).first;
    init_row(t, key, &it->second);
  }
  return &it->second;
}

}  // namespace

extern "C" {

void* pd_table_create(int dim, float init_range, uint64_t seed) {
  auto* t = new Table;
  t->dim = dim;
  t->init_range = init_range;
  t->seed = seed;
  return t;
}

void pd_table_destroy(void* table) { delete static_cast<Table*>(table); }

int pd_table_dim(void* table) { return static_cast<Table*>(table)->dim; }

int64_t pd_table_size(void* table) {
  auto* t = static_cast<Table*>(table);
  int64_t n = 0;
  for (int s = 0; s < kNumShards; ++s) {
    std::lock_guard<std::mutex> lk(t->locks[s]);
    n += static_cast<int64_t>(t->shards[s].size());
  }
  return n;
}

// out: [n, dim] row-major
void pd_table_pull(void* table, const int64_t* keys, int64_t n, float* out) {
  auto* t = static_cast<Table*>(table);
  for (int64_t i = 0; i < n; ++i) {
    int s = shard_of(keys[i]);
    std::lock_guard<std::mutex> lk(t->locks[s]);
    Row* r = find_or_create(t, keys[i]);
    memcpy(out + i * t->dim, r->w.data(), t->dim * sizeof(float));
  }
}

// grads: [n, dim]; duplicate keys accumulate sequentially (reference
// accessor semantics)
void pd_table_push_sgd(void* table, const int64_t* keys, const float* grads,
                       int64_t n, float lr) {
  auto* t = static_cast<Table*>(table);
  for (int64_t i = 0; i < n; ++i) {
    int s = shard_of(keys[i]);
    std::lock_guard<std::mutex> lk(t->locks[s]);
    Row* r = find_or_create(t, keys[i]);
    const float* g = grads + i * t->dim;
    for (int d = 0; d < t->dim; ++d) r->w[d] -= lr * g[d];
  }
}

void pd_table_push_adagrad(void* table, const int64_t* keys,
                           const float* grads, int64_t n, float lr,
                           float eps) {
  auto* t = static_cast<Table*>(table);
  for (int64_t i = 0; i < n; ++i) {
    int s = shard_of(keys[i]);
    std::lock_guard<std::mutex> lk(t->locks[s]);
    Row* r = find_or_create(t, keys[i]);
    if (r->g2.empty()) r->g2.assign(t->dim, 0.0f);
    const float* g = grads + i * t->dim;
    for (int d = 0; d < t->dim; ++d) {
      r->g2[d] += g[d] * g[d];
      r->w[d] -= lr * g[d] / (sqrtf(r->g2[d]) + eps);
    }
  }
}

// Binary format: i32 dim | i64 count | repeated (i64 key | f32*dim w |
// u8 has_g2 | [f32*dim g2])
int pd_table_save(void* table, const char* path) {
  auto* t = static_cast<Table*>(table);
  FILE* f = fopen(path, "wb");
  if (!f) return -1;
  // The row count cannot be snapshotted up front: a concurrent push may
  // insert keys while shards are written one lock at a time, making the
  // header disagree with the body (truncated/misaligned load).  Write a
  // placeholder, count rows actually written, then seek back and patch.
  int64_t count = 0;
  fwrite(&t->dim, sizeof(int), 1, f);
  long count_pos = ftell(f);
  fwrite(&count, sizeof(int64_t), 1, f);
  for (int s = 0; s < kNumShards; ++s) {
    std::lock_guard<std::mutex> lk(t->locks[s]);
    for (auto& kv : t->shards[s]) {
      fwrite(&kv.first, sizeof(int64_t), 1, f);
      fwrite(kv.second.w.data(), sizeof(float), t->dim, f);
      uint8_t has_g2 = kv.second.g2.empty() ? 0 : 1;
      fwrite(&has_g2, 1, 1, f);
      if (has_g2)
        fwrite(kv.second.g2.data(), sizeof(float), t->dim, f);
      ++count;
    }
  }
  if (fseek(f, count_pos, SEEK_SET) != 0) { fclose(f); return -4; }
  if (fwrite(&count, sizeof(int64_t), 1, f) != 1) { fclose(f); return -4; }
  // fclose flushes buffered writes; a failure here (disk full) means the
  // header patch may not have landed — report it rather than return a
  // valid-looking file whose header still says 0 rows.
  if (fclose(f) != 0) return -5;
  return 0;
}

int pd_table_load(void* table, const char* path) {
  auto* t = static_cast<Table*>(table);
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  int dim = 0;
  int64_t count = 0;
  if (fread(&dim, sizeof(int), 1, f) != 1 || dim != t->dim ||
      fread(&count, sizeof(int64_t), 1, f) != 1) {
    fclose(f);
    return -2;
  }
  for (int64_t i = 0; i < count; ++i) {
    int64_t key;
    if (fread(&key, sizeof(int64_t), 1, f) != 1) { fclose(f); return -3; }
    Row row;
    row.w.resize(dim);
    if (fread(row.w.data(), sizeof(float), dim, f)
        != static_cast<size_t>(dim)) { fclose(f); return -3; }
    uint8_t has_g2 = 0;
    if (fread(&has_g2, 1, 1, f) != 1) { fclose(f); return -3; }
    if (has_g2) {
      row.g2.resize(dim);
      if (fread(row.g2.data(), sizeof(float), dim, f)
          != static_cast<size_t>(dim)) { fclose(f); return -3; }
    }
    int s = shard_of(key);
    std::lock_guard<std::mutex> lk(t->locks[s]);
    t->shards[s][key] = std::move(row);
  }
  fclose(f);
  return 0;
}

}  // extern "C"
