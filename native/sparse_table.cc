// Memory sparse table — the parameter-server embedding store.
//
// Role parity with the reference PS sparse tables
// (paddle/fluid/distributed/ps/table/memory_sparse_table.cc — pull/push
// with in-table optimizer accessors, save/load;
// ctr_accessor.cc — show/click decay, ShowClickScore eviction;
// ssd_sparse_table.h — memory tier + disk overflow;
// memory_sparse_geo_table.h — async delta push).  Design here is new:
// sharded open hash maps guarded by per-shard mutexes, rows initialized
// deterministically from the key (splitmix64 -> uniform), the optimizer
// (SGD / Adagrad) applied inside the push so the host owns optimizer state
// for 100B-feature-scale embeddings while the TPU only sees dense pulled
// rows.  The disk tier is an append-only spill log + in-memory offset
// index (RocksDB role, without the dependency): when a shard exceeds its
// row budget the coldest rows (LRU tick) spill; pulls promote them back.
#include "paddle_native.h"

#include <fcntl.h>
#include <math.h>
#include <stdio.h>
#include <string.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <mutex>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

constexpr int kNumShards = 16;

struct Row {
  std::vector<float> w;    // embedding weights [dim]
  std::vector<float> g2;   // adagrad accumulator [dim] (lazily allocated)
  float show = 0.0f;       // CTR accessor stats (ctr_accessor.h layout)
  float click = 0.0f;
  int32_t unseen = 0;      // shrink cycles since last access
  uint64_t tick = 0;       // last-access counter (cold selection)
};

// disk-resident row: spill-log offset + the metadata shrink needs so
// eviction decisions never touch the disk.  `bytes` lets eviction and
// promotion account dead log space for compaction without a read.
struct DiskEnt {
  int64_t offset;
  int32_t bytes;
  float show;
  float click;
  int32_t unseen;
};

struct CtrParams {
  bool enabled = false;
  float nonclk_coeff = 0.1f;
  float click_coeff = 1.0f;
  float decay_rate = 0.98f;
  float delete_threshold = 0.8f;
  int32_t delete_after_unseen_days = 30;
};

struct Table {
  int dim;
  uint64_t seed;
  float init_range;
  std::unordered_map<int64_t, Row> shards[kNumShards];
  std::unordered_map<int64_t, DiskEnt> disk_index[kNumShards];
  std::mutex locks[kNumShards];
  std::atomic<uint64_t> clock{0};
  CtrParams ctr;
  // disk tier (0 = disabled)
  int64_t max_mem_rows = 0;
  int spill_fd = -1;
  int64_t spill_end = 0;  // append offset
  std::atomic<int64_t> spill_dead{0};  // bytes of superseded records
  std::mutex spill_mu;    // serializes appends (preads are lock-free)
  std::string spill_path;
  // geo recorder (reference geo_recorder.h ConcurrentSet role): when a
  // trainer ships deltas, the touched keys enter every OTHER trainer's
  // dirty set; geo_pull drains a trainer's set as (key, current row)
  // pairs — changed rows only, the server-initiated pull schedule.
  // Dirty sets shard by key with per-shard mutexes so concurrent
  // trainer pushes scale like the row store (review regression: one
  // table-global mutex serialized the whole geo path).
  int geo_trainers = 0;               // 0 = geo mode off
  // geo_dirty[trainer][shard]
  std::vector<std::vector<std::unordered_set<int64_t>>> geo_dirty;
  std::mutex geo_locks[kNumShards];
  std::mutex geo_mu;                  // guards init only
};

inline int shard_of(int64_t key) {
  return static_cast<uint64_t>(key) % kNumShards;
}

inline uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ull;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
  return x ^ (x >> 31);
}

// deterministic per-(key, slot) uniform init in [-range, range]
void init_row(Table* t, int64_t key, Row* row) {
  row->w.resize(t->dim);
  uint64_t state = splitmix64(static_cast<uint64_t>(key) ^ t->seed);
  for (int i = 0; i < t->dim; ++i) {
    state = splitmix64(state);
    double u = (state >> 11) * (1.0 / 9007199254740992.0);  // [0,1)
    row->w[i] = static_cast<float>((2.0 * u - 1.0) * t->init_range);
  }
}

// reference ctr_accessor.cc ShowClickScore
inline float show_click_score(const CtrParams& p, float show, float click) {
  return (show - click) * p.nonclk_coeff + click * p.click_coeff;
}

// ---- spill log ------------------------------------------------------------
// record: f32 show | f32 click | i32 unseen | u8 has_g2 | f32 w[dim]
//         | [f32 g2[dim]]   (key lives in the index)

int64_t spill_append(Table* t, const Row& row) {
  std::lock_guard<std::mutex> lk(t->spill_mu);
  int64_t off = t->spill_end;
  uint8_t has_g2 = row.g2.empty() ? 0 : 1;
  std::vector<char> buf;
  buf.reserve(13 + (1 + has_g2) * t->dim * 4);
  auto put = [&buf](const void* p, size_t n) {
    buf.insert(buf.end(), static_cast<const char*>(p),
               static_cast<const char*>(p) + n);
  };
  put(&row.show, 4);
  put(&row.click, 4);
  put(&row.unseen, 4);
  put(&has_g2, 1);
  put(row.w.data(), t->dim * 4);
  if (has_g2) put(row.g2.data(), t->dim * 4);
  ssize_t n = pwrite(t->spill_fd, buf.data(), buf.size(), off);
  if (n != static_cast<ssize_t>(buf.size())) return -1;
  t->spill_end += n;
  return off;
}

bool spill_read(Table* t, int64_t off, Row* row) {
  char hdr[13];
  if (pread(t->spill_fd, hdr, 13, off) != 13) return false;
  memcpy(&row->show, hdr, 4);
  memcpy(&row->click, hdr + 4, 4);
  memcpy(&row->unseen, hdr + 8, 4);
  uint8_t has_g2 = static_cast<uint8_t>(hdr[12]);
  row->w.resize(t->dim);
  if (pread(t->spill_fd, row->w.data(), t->dim * 4, off + 13) != t->dim * 4)
    return false;
  if (has_g2) {
    row->g2.resize(t->dim);
    if (pread(t->spill_fd, row->g2.data(), t->dim * 4,
              off + 13 + t->dim * 4) != t->dim * 4)
      return false;
  } else {
    row->g2.clear();
  }
  return true;
}

// caller holds shard lock s.  Spill the coldest half of the shard when it
// exceeds its budget (ssd_sparse_table role: hot rows stay resident).
void maybe_spill(Table* t, int s) {
  if (t->spill_fd < 0 || t->max_mem_rows <= 0) return;
  int64_t budget = std::max<int64_t>(1, t->max_mem_rows / kNumShards);
  auto& m = t->shards[s];
  if (static_cast<int64_t>(m.size()) <= budget) return;
  std::vector<std::pair<uint64_t, int64_t>> order;  // (tick, key)
  order.reserve(m.size());
  for (auto& kv : m) order.emplace_back(kv.second.tick, kv.first);
  size_t keep = static_cast<size_t>(budget) / 2 + 1;
  size_t n_spill = order.size() > keep ? order.size() - keep : 0;
  if (!n_spill) return;
  std::nth_element(order.begin(), order.begin() + n_spill, order.end());
  for (size_t i = 0; i < n_spill; ++i) {
    int64_t key = order[i].second;
    auto it = m.find(key);
    if (it == m.end()) continue;
    int64_t off = spill_append(t, it->second);
    if (off < 0) return;  // disk full: stop spilling, keep rows in memory
    int32_t bytes = 13 + (it->second.g2.empty() ? 1 : 2) * t->dim * 4;
    t->disk_index[s][key] = DiskEnt{off, bytes, it->second.show,
                                    it->second.click, it->second.unseen};
    m.erase(it);
  }
}

// Rewrite the spill log keeping only live (indexed) records.  Takes every
// shard lock (ascending order — callers hold NO locks) + the spill mutex,
// so offsets can be rewritten consistently.  Returns 0 / -1.
int spill_compact(Table* t) {
  std::unique_lock<std::mutex> shard_locks[kNumShards];
  for (int s = 0; s < kNumShards; ++s)
    shard_locks[s] = std::unique_lock<std::mutex>(t->locks[s]);
  std::lock_guard<std::mutex> lk(t->spill_mu);
  std::string tmp = t->spill_path + ".compact";
  int nfd = open(tmp.c_str(), O_RDWR | O_CREAT | O_TRUNC, 0644);
  if (nfd < 0) return -1;
  // stage new offsets; commit only after the file swap succeeds, so a
  // mid-compaction I/O failure leaves the old log + index fully intact
  std::vector<std::pair<DiskEnt*, int64_t>> staged;
  int64_t new_end = 0;
  for (int s = 0; s < kNumShards; ++s) {
    for (auto& kv : t->disk_index[s]) {
      std::vector<char> buf(kv.second.bytes);
      if (pread(t->spill_fd, buf.data(), buf.size(),
                kv.second.offset) != static_cast<ssize_t>(buf.size()) ||
          pwrite(nfd, buf.data(), buf.size(), new_end) !=
              static_cast<ssize_t>(buf.size())) {
        close(nfd);
        unlink(tmp.c_str());
        return -1;
      }
      staged.emplace_back(&kv.second, new_end);
      new_end += static_cast<int64_t>(buf.size());
    }
  }
  if (rename(tmp.c_str(), t->spill_path.c_str()) != 0) {
    close(nfd);
    unlink(tmp.c_str());
    return -1;
  }
  for (auto& p : staged) p.first->offset = p.second;
  close(t->spill_fd);
  t->spill_fd = nfd;
  t->spill_end = new_end;
  t->spill_dead.store(0);
  return 0;
}

// Opportunistic compaction trigger — called from public entry points
// while NO shard lock is held.  Keeps the log under ~2x live size.
void maybe_compact(Table* t) {
  if (t->spill_fd < 0) return;
  int64_t dead = t->spill_dead.load();
  if (dead > (1 << 20) && dead * 2 > t->spill_end) spill_compact(t);
}

// caller holds shard lock; resident row, promoted from disk, or fresh
Row* find_or_create(Table* t, int64_t key) {
  int s = shard_of(key);
  auto& m = t->shards[s];
  auto it = m.find(key);
  if (it == m.end()) {
    it = m.emplace(key, Row{}).first;
    auto dit = t->disk_index[s].find(key);
    bool promoted = false;
    if (dit != t->disk_index[s].end()) {
      promoted = spill_read(t, dit->second.offset, &it->second);
      if (!promoted) {
        // unreadable record (truncated/corrupt log): surface it — the
        // entry is dropped either way (size stays consistent), but a
        // silent re-init of trained weights must not pass unnoticed
        fprintf(stderr,
                "paddle_tpu sparse_table: spill record for key %lld "
                "unreadable; row re-initialized\n",
                static_cast<long long>(key));
        it->second = Row{};  // clear any partially-read w/g2
      }
      t->spill_dead.fetch_add(dit->second.bytes);
      t->disk_index[s].erase(dit);
    }
    if (!promoted) init_row(t, key, &it->second);
    // stamp the tick BEFORE spilling so the just-touched row is the
    // hottest and can't be selected as a spill victim
    it->second.tick = t->clock.fetch_add(1) + 1;
    maybe_spill(t, s);
    it = m.find(key);  // maybe_spill may rehash iterators
  }
  it->second.tick = t->clock.fetch_add(1) + 1;
  it->second.unseen = 0;
  return &it->second;
}

}  // namespace

extern "C" {

void* pd_table_create(int dim, float init_range, uint64_t seed) {
  auto* t = new Table;
  t->dim = dim;
  t->init_range = init_range;
  t->seed = seed;
  return t;
}

void pd_table_destroy(void* table) {
  auto* t = static_cast<Table*>(table);
  if (t->spill_fd >= 0) close(t->spill_fd);
  delete t;
}

int pd_table_dim(void* table) { return static_cast<Table*>(table)->dim; }

int64_t pd_table_size(void* table) {
  auto* t = static_cast<Table*>(table);
  int64_t n = 0;
  for (int s = 0; s < kNumShards; ++s) {
    std::lock_guard<std::mutex> lk(t->locks[s]);
    n += static_cast<int64_t>(t->shards[s].size()) +
         static_cast<int64_t>(t->disk_index[s].size());
  }
  return n;
}

int64_t pd_table_mem_rows(void* table) {
  auto* t = static_cast<Table*>(table);
  int64_t n = 0;
  for (int s = 0; s < kNumShards; ++s) {
    std::lock_guard<std::mutex> lk(t->locks[s]);
    n += static_cast<int64_t>(t->shards[s].size());
  }
  return n;
}

int64_t pd_table_disk_rows(void* table) {
  auto* t = static_cast<Table*>(table);
  int64_t n = 0;
  for (int s = 0; s < kNumShards; ++s) {
    std::lock_guard<std::mutex> lk(t->locks[s]);
    n += static_cast<int64_t>(t->disk_index[s].size());
  }
  return n;
}

// Disk overflow tier (reference ssd_sparse_table.h role).  Must be called
// before any rows spill; max_mem_rows bounds RESIDENT rows table-wide.
int pd_table_enable_disk(void* table, const char* path,
                         int64_t max_mem_rows) {
  auto* t = static_cast<Table*>(table);
  // re-enabling with live spilled rows would O_TRUNC the log their index
  // offsets point into (or alias offsets in a new file) — refuse
  if (pd_table_disk_rows(table) > 0) return -2;
  int fd = open(path, O_RDWR | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return -1;
  if (t->spill_fd >= 0) close(t->spill_fd);
  t->spill_fd = fd;
  t->spill_end = 0;
  t->spill_dead.store(0);
  t->spill_path = path;
  t->max_mem_rows = max_mem_rows;
  return 0;
}

// CTR accessor config (reference ctr_accessor.cc ctor params)
void pd_table_set_ctr(void* table, float nonclk_coeff, float click_coeff,
                      float decay_rate, float delete_threshold,
                      int delete_after_unseen_days) {
  auto* t = static_cast<Table*>(table);
  t->ctr.enabled = true;
  t->ctr.nonclk_coeff = nonclk_coeff;
  t->ctr.click_coeff = click_coeff;
  t->ctr.decay_rate = decay_rate;
  t->ctr.delete_threshold = delete_threshold;
  t->ctr.delete_after_unseen_days = delete_after_unseen_days;
}

// out: [n, dim] row-major
void pd_table_pull(void* table, const int64_t* keys, int64_t n, float* out) {
  auto* t = static_cast<Table*>(table);
  maybe_compact(t);
  for (int64_t i = 0; i < n; ++i) {
    int s = shard_of(keys[i]);
    std::lock_guard<std::mutex> lk(t->locks[s]);
    Row* r = find_or_create(t, keys[i]);
    memcpy(out + i * t->dim, r->w.data(), t->dim * sizeof(float));
  }
}

// grads: [n, dim]; duplicate keys accumulate sequentially (reference
// accessor semantics)
void pd_table_push_sgd(void* table, const int64_t* keys, const float* grads,
                       int64_t n, float lr) {
  auto* t = static_cast<Table*>(table);
  maybe_compact(t);
  for (int64_t i = 0; i < n; ++i) {
    int s = shard_of(keys[i]);
    std::lock_guard<std::mutex> lk(t->locks[s]);
    Row* r = find_or_create(t, keys[i]);
    const float* g = grads + i * t->dim;
    for (int d = 0; d < t->dim; ++d) r->w[d] -= lr * g[d];
  }
}

void pd_table_push_adagrad(void* table, const int64_t* keys,
                           const float* grads, int64_t n, float lr,
                           float eps) {
  auto* t = static_cast<Table*>(table);
  maybe_compact(t);
  for (int64_t i = 0; i < n; ++i) {
    int s = shard_of(keys[i]);
    std::lock_guard<std::mutex> lk(t->locks[s]);
    Row* r = find_or_create(t, keys[i]);
    if (r->g2.empty()) r->g2.assign(t->dim, 0.0f);
    const float* g = grads + i * t->dim;
    for (int d = 0; d < t->dim; ++d) {
      r->g2[d] += g[d] * g[d];
      r->w[d] -= lr * g[d] / (sqrtf(r->g2[d]) + eps);
    }
  }
}

// GeoSGD async apply: w += delta (reference memory_sparse_geo_table's
// PushSparse — trainers train local replicas and ship deltas)
void pd_table_push_delta(void* table, const int64_t* keys,
                         const float* deltas, int64_t n) {
  auto* t = static_cast<Table*>(table);
  maybe_compact(t);
  for (int64_t i = 0; i < n; ++i) {
    int s = shard_of(keys[i]);
    std::lock_guard<std::mutex> lk(t->locks[s]);
    Row* r = find_or_create(t, keys[i]);
    const float* d = deltas + i * t->dim;
    for (int j = 0; j < t->dim; ++j) r->w[j] += d[j];
  }
}

// Geo mode (reference memory_sparse_geo_table.h + geo_recorder.h):
// per-trainer dirty-key queues so trainers pull CHANGED rows only.

int pd_table_geo_init(void* table, int trainer_num) {
  auto* t = static_cast<Table*>(table);
  if (trainer_num <= 0) return -1;
  std::lock_guard<std::mutex> lk(t->geo_mu);
  if (t->geo_trainers == trainer_num) return 0;  // idempotent: every
  // trainer calls this at startup; re-init must not drop queued deltas
  if (t->geo_trainers != 0) return -2;           // conflicting world
  t->geo_dirty.assign(
      trainer_num, std::vector<std::unordered_set<int64_t>>(kNumShards));
  t->geo_trainers = trainer_num;
  return 0;
}

int pd_table_geo_push(void* table, int trainer_id, const int64_t* keys,
                      const float* deltas, int64_t n) {
  auto* t = static_cast<Table*>(table);
  // invalid trainer ids must fail loudly BEFORE mutating anything: an
  // out-of-range id would pollute every queue including the sender's
  // (review regression)
  if (trainer_id < 0 || trainer_id >= t->geo_trainers) return -1;
  pd_table_push_delta(table, keys, deltas, n);
  // bucket keys by shard in one O(n) pass, then take each shard lock
  // once over its bucket — the per-shard full rescan was
  // O(kNumShards * n) under locks (advisor finding, round 4)
  std::vector<std::vector<int64_t>> buckets(kNumShards);
  for (int64_t i = 0; i < n; ++i) buckets[shard_of(keys[i])].push_back(keys[i]);
  for (int s = 0; s < kNumShards; ++s) {
    if (buckets[s].empty()) continue;
    std::lock_guard<std::mutex> lk(t->geo_locks[s]);
    for (int64_t k : buckets[s]) {
      for (int tr = 0; tr < t->geo_trainers; ++tr) {
        if (tr != trainer_id) t->geo_dirty[tr][s].insert(k);
      }
    }
  }
  return 0;
}

int64_t pd_table_geo_pull_count(void* table, int trainer_id) {
  auto* t = static_cast<Table*>(table);
  if (trainer_id < 0 || trainer_id >= t->geo_trainers) return -1;
  int64_t total = 0;
  for (int s = 0; s < kNumShards; ++s) {
    std::lock_guard<std::mutex> lk(t->geo_locks[s]);
    total += static_cast<int64_t>(t->geo_dirty[trainer_id][s].size());
  }
  return total;
}

int64_t pd_table_geo_pull(void* table, int trainer_id, int64_t* keys_out,
                          float* vals_out, int64_t max_n) {
  auto* t = static_cast<Table*>(table);
  if (trainer_id < 0 || trainer_id >= t->geo_trainers) return -1;
  std::vector<int64_t> keys;
  for (int s = 0; s < kNumShards &&
       static_cast<int64_t>(keys.size()) < max_n; ++s) {
    std::lock_guard<std::mutex> lk(t->geo_locks[s]);
    auto& set = t->geo_dirty[trainer_id][s];
    for (auto it = set.begin();
         it != set.end() && static_cast<int64_t>(keys.size()) < max_n;) {
      keys.push_back(*it);
      it = set.erase(it);
    }
  }
  // rows are read AFTER the sets drain: a concurrent push between the
  // drain and this read re-inserts the key, so no update is lost
  pd_table_pull(table, keys.data(), static_cast<int64_t>(keys.size()),
                vals_out);
  memcpy(keys_out, keys.data(), keys.size() * sizeof(int64_t));
  return static_cast<int64_t>(keys.size());
}

// CTR stats accumulation (reference CtrCommonPushValue show/click)
void pd_table_push_show_click(void* table, const int64_t* keys,
                              const float* shows, const float* clicks,
                              int64_t n) {
  auto* t = static_cast<Table*>(table);
  for (int64_t i = 0; i < n; ++i) {
    int s = shard_of(keys[i]);
    std::lock_guard<std::mutex> lk(t->locks[s]);
    Row* r = find_or_create(t, keys[i]);
    r->show += shows[i];
    r->click += clicks[i];
  }
}

// out: [n, 3] (show, click, unseen) — resident or disk metadata
void pd_table_get_meta(void* table, const int64_t* keys, int64_t n,
                       float* out) {
  auto* t = static_cast<Table*>(table);
  for (int64_t i = 0; i < n; ++i) {
    int s = shard_of(keys[i]);
    std::lock_guard<std::mutex> lk(t->locks[s]);
    auto it = t->shards[s].find(keys[i]);
    if (it != t->shards[s].end()) {
      out[i * 3] = it->second.show;
      out[i * 3 + 1] = it->second.click;
      out[i * 3 + 2] = static_cast<float>(it->second.unseen);
      continue;
    }
    auto dit = t->disk_index[s].find(keys[i]);
    if (dit != t->disk_index[s].end()) {
      out[i * 3] = dit->second.show;
      out[i * 3 + 1] = dit->second.click;
      out[i * 3 + 2] = static_cast<float>(dit->second.unseen);
    } else {
      out[i * 3] = out[i * 3 + 1] = -1.0f;
      out[i * 3 + 2] = -1.0f;
    }
  }
}

// One shrink cycle (reference ctr_accessor.cc Shrink, called by the PS
// server's daily shrink): decay show/click, age unseen_days, evict rows
// whose ShowClickScore fell under the threshold or that aged out.
// Disk-tier rows evict by dropping their index entry (space reclaimed at
// the next save/compaction).  Returns rows evicted.
int64_t pd_table_shrink(void* table) {
  auto* t = static_cast<Table*>(table);
  if (!t->ctr.enabled) return 0;
  const CtrParams& p = t->ctr;
  int64_t evicted = 0;
  for (int s = 0; s < kNumShards; ++s) {
    std::lock_guard<std::mutex> lk(t->locks[s]);
    auto& m = t->shards[s];
    for (auto it = m.begin(); it != m.end();) {
      Row& r = it->second;
      r.show *= p.decay_rate;
      r.click *= p.decay_rate;
      r.unseen += 1;
      float score = show_click_score(p, r.show, r.click);
      if (score < p.delete_threshold ||
          r.unseen > p.delete_after_unseen_days) {
        it = m.erase(it);
        ++evicted;
      } else {
        ++it;
      }
    }
    auto& di = t->disk_index[s];
    for (auto it = di.begin(); it != di.end();) {
      DiskEnt& e = it->second;
      e.show *= p.decay_rate;
      e.click *= p.decay_rate;
      e.unseen += 1;
      float score = show_click_score(p, e.show, e.click);
      if (score < p.delete_threshold ||
          e.unseen > p.delete_after_unseen_days) {
        t->spill_dead.fetch_add(e.bytes);
        it = di.erase(it);
        ++evicted;
      } else {
        ++it;
      }
    }
  }
  return evicted;
}

// Binary format v2: magic "PDT2" | i32 dim | i64 count | repeated
// (i64 key | f32 show | f32 click | i32 unseen | u8 has_g2 | f32*dim w |
//  [f32*dim g2]).  v1 (no magic: i32 dim | i64 count | (key|w|has_g2|[g2]))
// still loads — version detection peeks the first 4 bytes.  Saving walks
// memory AND the disk tier (compaction: dead spill records drop out).
int pd_table_save(void* table, const char* path) {
  auto* t = static_cast<Table*>(table);
  FILE* f = fopen(path, "wb");
  if (!f) return -1;
  const char magic[4] = {'P', 'D', 'T', '2'};
  fwrite(magic, 1, 4, f);
  // The row count cannot be snapshotted up front: a concurrent push may
  // insert keys while shards are written one lock at a time, making the
  // header disagree with the body (truncated/misaligned load).  Write a
  // placeholder, count rows actually written, then seek back and patch.
  int64_t count = 0;
  fwrite(&t->dim, sizeof(int), 1, f);
  long count_pos = ftell(f);
  fwrite(&count, sizeof(int64_t), 1, f);
  auto write_row = [&](int64_t key, const Row& row) {
    fwrite(&key, sizeof(int64_t), 1, f);
    fwrite(&row.show, sizeof(float), 1, f);
    fwrite(&row.click, sizeof(float), 1, f);
    fwrite(&row.unseen, sizeof(int32_t), 1, f);
    uint8_t has_g2 = row.g2.empty() ? 0 : 1;
    fwrite(&has_g2, 1, 1, f);
    fwrite(row.w.data(), sizeof(float), t->dim, f);
    if (has_g2) fwrite(row.g2.data(), sizeof(float), t->dim, f);
    ++count;
  };
  for (int s = 0; s < kNumShards; ++s) {
    std::lock_guard<std::mutex> lk(t->locks[s]);
    for (auto& kv : t->shards[s]) write_row(kv.first, kv.second);
    for (auto& kv : t->disk_index[s]) {
      Row row;
      if (!spill_read(t, kv.second.offset, &row)) { fclose(f); return -6; }
      row.show = kv.second.show;       // index metadata is authoritative
      row.click = kv.second.click;     // (shrink decays it in place)
      row.unseen = kv.second.unseen;
      write_row(kv.first, row);
    }
  }
  if (fseek(f, count_pos, SEEK_SET) != 0) { fclose(f); return -4; }
  if (fwrite(&count, sizeof(int64_t), 1, f) != 1) { fclose(f); return -4; }
  // fclose flushes buffered writes; a failure here (disk full) means the
  // header patch may not have landed — report it rather than return a
  // valid-looking file whose header still says 0 rows.
  if (fclose(f) != 0) return -5;
  return 0;
}

int pd_table_load(void* table, const char* path) {
  auto* t = static_cast<Table*>(table);
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  char magic[4];
  if (fread(magic, 1, 4, f) != 4) { fclose(f); return -2; }
  bool v2 = memcmp(magic, "PDT2", 4) == 0;
  int dim = 0;
  int64_t count = 0;
  if (v2) {
    if (fread(&dim, sizeof(int), 1, f) != 1) { fclose(f); return -2; }
  } else {
    memcpy(&dim, magic, 4);  // v1: the first field IS the dim
  }
  if (dim != t->dim || fread(&count, sizeof(int64_t), 1, f) != 1) {
    fclose(f);
    return -2;
  }
  for (int64_t i = 0; i < count; ++i) {
    int64_t key;
    if (fread(&key, sizeof(int64_t), 1, f) != 1) { fclose(f); return -3; }
    Row row;
    if (v2) {
      if (fread(&row.show, sizeof(float), 1, f) != 1 ||
          fread(&row.click, sizeof(float), 1, f) != 1 ||
          fread(&row.unseen, sizeof(int32_t), 1, f) != 1) {
        fclose(f);
        return -3;
      }
    }
    // v2 stores has_g2 before w, v1 after — the w read is shared
    uint8_t has_g2 = 0;
    if (v2 && fread(&has_g2, 1, 1, f) != 1) { fclose(f); return -3; }
    row.w.resize(dim);
    if (fread(row.w.data(), sizeof(float), dim, f)
        != static_cast<size_t>(dim)) { fclose(f); return -3; }
    if (!v2 && fread(&has_g2, 1, 1, f) != 1) { fclose(f); return -3; }
    if (has_g2) {
      row.g2.resize(dim);
      if (fread(row.g2.data(), sizeof(float), dim, f)
          != static_cast<size_t>(dim)) { fclose(f); return -3; }
    }
    int s = shard_of(key);
    std::lock_guard<std::mutex> lk(t->locks[s]);
    t->shards[s][key] = std::move(row);
    auto dit = t->disk_index[s].find(key);
    if (dit != t->disk_index[s].end()) {
      // loaded copy supersedes the spilled one; its record is now dead
      t->spill_dead.fetch_add(dit->second.bytes);
      t->disk_index[s].erase(dit);
    }
    maybe_spill(t, s);
  }
  fclose(f);
  return 0;
}

}  // extern "C"
