"""Benchmark: GPT-124M causal-LM training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline = achieved MFU / 0.45 (the BASELINE.md north-star MFU target) —
the reference repo publishes no absolute numbers (SURVEY §6), so the target
ratio is the honest comparison.

Structure: the parent process NEVER imports jax.  A wedged TPU tunnel makes
``import jax`` hang outright (site hooks capture env at interpreter startup
— observed live in round 2), so the measurement runs in a worker subprocess
under a hard timeout; on failure it retries, then falls back to a CPU worker
with the TPU plugin env scrubbed, and always emits exactly one JSON line.
"""

import json
import os
import subprocess
import sys
import time
import traceback

import numpy as np

_REPO_DIR = os.path.dirname(os.path.abspath(__file__))


def _emit(obj):
    print(json.dumps(obj))


def _run_worker(timeout, cpu=False):
    """Run this file with --worker in a subprocess; returns (json_line, err)."""
    env = dict(os.environ)
    if cpu:
        for var in ("PALLAS_AXON_POOL_IPS", "AXON_POOL_SVC_OVERRIDE",
                    "AXON_LOOPBACK_RELAY"):
            env.pop(var, None)
        env["JAX_PLATFORMS"] = "cpu"
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--worker"],
            env=env, cwd=_REPO_DIR, timeout=timeout,
            capture_output=True, text=True)
    except subprocess.TimeoutExpired as e:
        # the worker prints the primary JSON line BEFORE the secondary
        # llama config runs — salvage it if the hang came later
        partial = (e.stdout or b"")
        if isinstance(partial, bytes):
            partial = partial.decode(errors="replace")
        for line in reversed(partial.strip().splitlines()):
            line = line.strip()
            if line.startswith("{") and line.endswith("}"):
                try:
                    json.loads(line)
                    return line, None
                except ValueError:
                    continue
        return None, f"worker timed out after {timeout}s (cpu={cpu})"
    for line in reversed(proc.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{") and line.endswith("}"):
            try:
                json.loads(line)
                return line, None
            except ValueError:
                continue
    tail = (proc.stderr or proc.stdout or "").strip()[-400:]
    return None, f"worker rc={proc.returncode} (cpu={cpu}): {tail}"


def orchestrate():
    errs = []
    for attempt, timeout in enumerate((900, 600)):
        line, err = _run_worker(timeout)
        if line is not None:
            print(line)
            return
        errs.append(err)
        time.sleep(10)
    line, err = _run_worker(600, cpu=True)
    if line is not None:
        obj = json.loads(line)
        obj["error"] = "; ".join(errs)
        _emit(obj)
        return
    errs.append(err)
    _emit({"metric": "gpt124m_train_tokens_per_sec_per_chip",
           "value": 0.0, "unit": "tokens/s", "vs_baseline": 0.0,
           "error": "; ".join(errs)})


def _init_backend(retries=3, backoff=(5, 15, 30)):
    """Initialize the jax backend, retrying TPU init and falling back to CPU.

    Returns the backend platform name.  Never raises: a dead TPU tunnel must
    degrade to a CPU measurement with an "error" note, not an rc=1 traceback
    (round-1 failure mode: BENCH_r01.json rc=1, parsed null).
    """
    import jax

    last_err = None
    for attempt in range(retries):
        try:
            jax.devices()
            return jax.default_backend(), None
        except Exception as e:  # backend init raised (e.g. UNAVAILABLE)
            last_err = e
            if attempt < retries - 1:
                time.sleep(backoff[min(attempt, len(backoff) - 1)])
    # terminal: force the host platform
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    try:
        jax.clear_backends()
    except Exception:
        pass
    try:
        jax.devices()
        return jax.default_backend(), f"tpu init failed, cpu fallback: {last_err}"
    except Exception as e:
        return None, f"no backend available: {e}"


def peak_flops_per_chip():
    """bf16 peak for the attached TPU generation; CPU fallback is nominal."""
    import jax
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "").lower()
    if "v5 lite" in kind or "v5e" in kind:
        return 197e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v4" in kind:
        return 275e12
    if "v6" in kind or "trillium" in kind:
        return 918e12
    return 1e12  # CPU: nominal, MFU not meaningful


def main():
    backend, init_note = _init_backend()
    if backend is None:
        print(json.dumps({
            "metric": "gpt124m_train_tokens_per_sec_per_chip",
            "value": 0.0, "unit": "tokens/s", "vs_baseline": 0.0,
            "error": init_note,
        }))
        return

    on_tpu = backend not in ("cpu",)

    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.gpt import gpt_124m, gpt_tiny

    paddle.seed(0)
    if on_tpu:
        cfg = dict(batch=8, seq=512)
        model = gpt_124m(hidden_dropout_prob=0.0,
                         attention_probs_dropout_prob=0.0)
        model = paddle.amp.decorate(model, level="O2", dtype="bfloat16")
        steps, warmup = 20, 3
    else:
        cfg = dict(batch=4, seq=128)
        model = gpt_tiny(num_layers=4, hidden_size=128,
                         max_position_embeddings=128)
        steps, warmup = 5, 2

    tok_s, mfu = _measure(model, cfg, steps, warmup, seed=0)
    out = {
        "metric": "gpt124m_train_tokens_per_sec_per_chip" if on_tpu
        else "gpt_tiny_cpu_tokens_per_sec",
        "value": round(tok_s, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.45, 4),
    }
    if init_note:
        out["error"] = init_note
    # Print the primary result NOW: if the secondary llama config wedges
    # past the worker timeout, the parent salvages this line instead of
    # discarding the whole measurement.
    print(json.dumps(out), flush=True)

    if on_tpu:
        # batch 8 x 512 under-saturates the MXU (r5 measured 30.9% MFU);
        # 124M params use ~2.5GB for params+grads+opt, leaving v5e HBM
        # room for much larger batches.  Measure batch 32 and take the
        # better number as the headline (OOM falls back cleanly).
        try:
            tok32, mfu32 = _measure(model, dict(batch=32, seq=512),
                                    12, 2, seed=0)
            out["b32_tokens_per_sec"] = round(tok32, 1)
            out["b32_mfu"] = round(mfu32, 4)
            if mfu32 > mfu:
                out["value"] = round(tok32, 1)
                out["vs_baseline"] = round(mfu32 / 0.45, 4)
                out["config"] = "batch=32,seq=512"
        except Exception as e:  # OOM etc: the batch-8 line stands
            out["b32_error"] = str(e)[:160]
        print(json.dumps(out), flush=True)

    # Second measured config: Llama-family decoder (RoPE/GQA/SwiGLU) —
    # the parent takes the LAST valid JSON line, so re-emit the combined
    # record (extra fields; the driver reads metric/value)
    try:
        from paddle_tpu.models.llama import llama_160m, llama_tiny

        paddle.seed(1)
        if on_tpu:
            lmodel = paddle.amp.decorate(llama_160m(), level="O2",
                                         dtype="bfloat16")
            lcfg, lsteps, lwarm = dict(batch=8, seq=512), 10, 2
        else:
            lmodel = llama_tiny()
            lcfg, lsteps, lwarm = dict(batch=4, seq=64), 3, 1
        ltok_s, lmfu = _measure(lmodel, lcfg, lsteps, lwarm, seed=1)
        out.update({
            "llama_metric": "llama160m_train_tokens_per_sec_per_chip"
            if on_tpu else "llama_tiny_cpu_tokens_per_sec",
            "llama_value": round(ltok_s, 1),
            "llama_vs_baseline": round(lmfu / 0.45, 4),
        })
    except Exception as e:  # secondary config must never kill the line
        out["llama_error"] = str(e)[:200]
    print(json.dumps(out), flush=True)


def _measure(model, cfg, steps, warmup, seed):
    """Shared measurement scaffold: warmup, synced timed loop, MFU."""
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.jit import TrainStep

    n_params = sum(p.size for p in model.parameters())
    opt = optimizer.AdamW(learning_rate=1e-4,
                          parameters=model.parameters())
    step = TrainStep(model,
                     lambda logits, labels: model.loss(logits, labels), opt)
    rng = np.random.RandomState(seed)
    vocab = model.config.vocab_size
    ids = paddle.to_tensor(
        rng.randint(0, vocab, (cfg["batch"], cfg["seq"])).astype(np.int32))
    labels = paddle.to_tensor(
        rng.randint(0, vocab, (cfg["batch"], cfg["seq"])).astype(np.int32))
    for _ in range(warmup):
        loss = step(ids, labels)
    float(loss.numpy())  # sync
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(ids, labels)
    final = float(loss.numpy())  # sync
    dt = time.perf_counter() - t0
    assert np.isfinite(final), "loss diverged during bench"
    tok_s = cfg["batch"] * cfg["seq"] * steps / dt
    mfu = tok_s * 6.0 * n_params / peak_flops_per_chip()
    return tok_s, mfu


if __name__ == "__main__":
    if "--worker" in sys.argv:
        try:
            main()
        except Exception:
            # Always emit exactly one parseable JSON line, even on failure.
            print(json.dumps({
                "metric": "gpt124m_train_tokens_per_sec_per_chip",
                "value": 0.0, "unit": "tokens/s", "vs_baseline": 0.0,
                "error": traceback.format_exc(limit=3).replace("\n", " | "),
            }))
            sys.exit(0)
    else:
        orchestrate()
